"""File-fed datasets + the dataset trainer loop (reference: the
Trainer/DeviceWorker stack — fluid/dataset.py InMemoryDataset/QueueDataset,
trainer_desc.py, device_worker.py; driven by
``Executor.train_from_dataset`` (fluid/executor.py:1629)).

The reference pumps example files through pipe commands into per-thread
DeviceWorkers that each run the program on their feed slice.  TPU-native
shape of the same capability: files are parsed on background threads into
host batches, double-buffered onto the device, and ONE jitted train step
consumes them — thread-parallel *IO*, SPMD *compute* (the reference's
N device-worker threads collapse into the XLA program per SURVEY §7).

File format: one example per line.  The default parser reads
whitespace-separated floats with the LAST column as an int label; pass
``parse_fn(line) -> tuple(np.ndarray, ...)`` for anything else (the
reference's pipe_command equivalent — a parsing hook, minus the subprocess).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


def _default_parse(line: str):
    vals = line.split()
    if not vals:
        return None
    feats = np.asarray([float(v) for v in vals[:-1]], np.float32)
    label = np.int64(int(float(vals[-1])))
    return feats, label


class DatasetBase:
    """Common config surface (reference fluid/dataset.py DatasetBase)."""

    def __init__(self):
        self._filelist: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._parse_fn: Callable = _default_parse
        self._use_var_names: List[str] = []

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self._thread_num = max(1, int(thread_num))

    def set_use_var(self, var_list):
        self._use_var_names = [getattr(v, "name", str(v)) for v in var_list]

    def set_pipe_command(self, pipe_command):
        """The reference shells out to ``pipe_command`` per file; here the
        parsing hook is a python callable — pass it via ``set_parse_fn``."""
        raise NotImplementedError(
            "pipe subprocess commands are not supported; use "
            "set_parse_fn(callable) for custom line parsing")

    def set_parse_fn(self, fn: Callable):
        self._parse_fn = fn

    # -- iteration ---------------------------------------------------------
    def _parse_file(self, path, native_threads=None):
        """All examples of one file, as a list — shared by the streaming
        iterator and the threaded bulk loader.

        Hot path: the C++ mmap parser (csrc/slot_feed.cpp ≙
        MultiSlotDataFeed) when the default dense format applies; anything it
        can't take (custom parse_fn, non-numeric content, empty/unreadable
        file, no toolchain) falls back to the Python line loop, which keeps
        the old error semantics (FileNotFoundError for missing paths, zero
        examples for empty files)."""
        if self._parse_fn is _default_parse:
            from .slot_feed import parse_dense_file
            try:
                parsed = parse_dense_file(
                    path, threads=native_threads or self._thread_num)
            except (ValueError, OSError):
                parsed = None
            if parsed is not None:
                feats, labels = parsed
                return [(feats[i], labels[i]) for i in range(feats.shape[0])]
        out = []
        with open(path) as f:
            for line in f:
                ex = self._parse_fn(line.rstrip("\n"))
                if ex is not None:
                    out.append(ex)
        return out

    def _example_stream(self):
        for path in self._filelist:
            yield from self._parse_file(path)

    def _batches_from(self, examples):
        buf = []
        for ex in examples:
            buf.append(ex)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)

    @staticmethod
    def _collate(buf):
        # reuse the DataLoader's collate (handles ndarray/Tensor/tuple/dict
        # recursively, numpy output); tuple-ify the top level for unpacking
        # into the trainer-loop program(*batch)
        from . import default_collate_fn
        out = default_collate_fn(buf)
        return tuple(out) if isinstance(out, list) else out


class InMemoryDataset(DatasetBase):
    """Load → (shuffle) → iterate from memory (reference InMemoryDataset:
    load_into_memory / local_shuffle / global_shuffle / release_memory)."""

    def __init__(self):
        super().__init__()
        self._examples: Optional[list] = None

    def load_into_memory(self):
        # thread-parallel file parsing (the reference's per-thread channels)
        if len(self._filelist) <= 1 or self._thread_num == 1:
            self._examples = list(self._example_stream())
            return
        # per-file result slots keep example order == filelist order no
        # matter which thread finishes first (deterministic seeded shuffles)
        slots: List = [None] * len(self._filelist)
        errors: List[BaseException] = []
        err_lock = threading.Lock()
        files = queue.Queue()
        for i, p in enumerate(self._filelist):
            files.put((i, p))

        def worker():
            while True:
                try:
                    i, path = files.get_nowait()
                except queue.Empty:
                    return
                try:
                    # each worker parses one file: 1 native thread apiece
                    slots[i] = self._parse_file(path, native_threads=1)
                except BaseException as e:  # propagate to the caller
                    with err_lock:
                        errors.append(e)
                    return

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._thread_num)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        self._examples = [ex for local in slots for ex in (local or [])]

    def local_shuffle(self, seed: Optional[int] = None):
        if self._examples is None:
            raise RuntimeError("call load_into_memory() before local_shuffle()")
        rng = np.random.RandomState(seed)
        rng.shuffle(self._examples)

    def global_shuffle(self, fleet=None, thread_num=None):
        """Single-host build: global == local shuffle (the reference ships
        examples between trainers; with SPMD data sharding each host draws
        from the same shuffled order)."""
        self.local_shuffle()

    def release_memory(self):
        self._examples = None

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._examples or [])

    def __iter__(self):
        if self._examples is None:
            raise RuntimeError("call load_into_memory() first")
        return self._batches_from(iter(self._examples))


class QueueDataset(DatasetBase):
    """Streaming dataset: batches come straight off the file readers with a
    bounded prefetch queue (reference QueueDataset's channel semantics)."""

    def __init__(self, capacity: int = 64):
        super().__init__()
        self._capacity = capacity

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._capacity)
        DONE = object()
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # iterator (early break) — otherwise the thread + open file
            # handle would leak, blocked on a full queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for b in self._batches_from(self._example_stream()):
                    if not put(b):
                        return
                put(DONE)
            except BaseException as e:  # surface reader errors, don't EOF
                put(e)

        threading.Thread(target=producer, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
