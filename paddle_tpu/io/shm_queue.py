"""Shared-memory batch queue over the native ring (csrc/shm_ring.cpp).

Worker processes serialize collated numpy batches into a process-shared
ring; the main process pops them.  ≙ reference dataloader_iter.py:336
(worker processes + shared-memory mmap tensors) + pybind/reader_py.cc
(C++ BlockingQueue) — one native component instead of two.

Serialization is a minimal header + raw array bytes (no pickle on the hot
path): [u32 tag][u32 n_arrays] then per array
[u8 dtype_len][dtype bytes][u8 ndim][u64 shape...] [u64 nbytes][raw bytes].
Nested list/dict batch structure is carried separately as a pickled
template (tiny, once per batch shape).
"""

from __future__ import annotations

import ctypes
import logging
import os
import pickle
import struct
from typing import Any, List, Optional, Tuple

import numpy as np

from ..csrc import load_library

logger = logging.getLogger(__name__)


class _Lib:
    _lib = None

    @classmethod
    def get(cls):
        if cls._lib is None:
            lib = load_library("shm_ring")
            lib.shm_ring_open.restype = ctypes.c_void_p
            lib.shm_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_int]
            lib.shm_ring_push.restype = ctypes.c_int
            lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_uint64, ctypes.c_long]
            lib.shm_ring_pop.restype = ctypes.c_int64
            lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_uint64, ctypes.c_long,
                                         ctypes.POINTER(ctypes.c_uint64)]
            lib.shm_ring_close.argtypes = [ctypes.c_void_p]
            lib.shm_ring_used.restype = ctypes.c_uint64
            lib.shm_ring_used.argtypes = [ctypes.c_void_p]
            lib.shm_ring_detach.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
            cls._lib = lib
        return cls._lib


class ShmQueue:
    """Bounded blocking byte-message queue in POSIX shared memory."""

    def __init__(self, name: str, capacity: int = 64 << 20, owner: bool = True):
        self._lib = _Lib.get()
        self.name = name.encode()
        self.capacity = capacity
        self.owner = owner
        self._ring = self._lib.shm_ring_open(self.name, capacity, 1 if owner else 0)
        if not self._ring:
            raise OSError(f"shm_ring_open({name!r}, owner={owner}) failed")
        self._buf = ctypes.create_string_buffer(1 << 20)

    def put(self, data: bytes, timeout: Optional[float] = None) -> None:
        ms = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.shm_ring_push(self._ring, data, len(data), ms)
        if rc == -1:
            raise TimeoutError("shm queue push timed out")
        if rc == -2:
            raise EOFError("shm queue closed")
        if rc == -3:
            raise ValueError(f"message of {len(data)} bytes exceeds ring "
                             f"capacity {self.capacity}")
        if rc != 0:
            raise OSError(f"shm_ring_push rc={rc}")

    def get(self, timeout: Optional[float] = None) -> bytes:
        ms = -1 if timeout is None else int(timeout * 1000)
        need = ctypes.c_uint64(0)
        rc = self._lib.shm_ring_pop(self._ring, self._buf, len(self._buf), ms,
                                    ctypes.byref(need))
        if rc == -5:  # grow the receive buffer and retry (message intact)
            self._buf = ctypes.create_string_buffer(int(need.value))
            rc = self._lib.shm_ring_pop(self._ring, self._buf, len(self._buf),
                                        ms, ctypes.byref(need))
        if rc == -1:
            raise TimeoutError("shm queue pop timed out")
        if rc == -2:
            raise EOFError("shm queue closed")
        if rc < 0:
            raise OSError(f"shm_ring_pop rc={rc}")
        return self._buf.raw[:rc]

    def close(self) -> None:
        if self._ring:
            self._lib.shm_ring_close(self._ring)

    def __del__(self):
        try:
            if getattr(self, "_ring", None):
                self._lib.shm_ring_detach(self._ring, self.capacity)
                if self.owner:
                    self._lib.shm_ring_unlink(self.name)
                self._ring = None
        except (OSError, AttributeError) as e:
            # native detach/unlink failing at GC means the segment leaks
            # until reboot — that deserves a debug line, not silence
            logger.debug("ShmQueue.__del__: detach failed for %s: %s",
                         getattr(self, "name", "?"), e)


# ---------------------------------------------------------------------- codec

def _flatten(batch) -> Tuple[Any, List[np.ndarray]]:
    arrays: List[np.ndarray] = []

    def rec(x):
        if isinstance(x, np.ndarray):
            arrays.append(np.ascontiguousarray(x))
            return ("__a__", len(arrays) - 1)
        if isinstance(x, (list, tuple)):
            return [rec(v) for v in x] if isinstance(x, list) else \
                tuple(rec(v) for v in x)
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x

    return rec(batch), arrays


def _unflatten(template, arrays: List[np.ndarray]):
    def rec(x):
        if isinstance(x, tuple) and len(x) == 2 and x[0] == "__a__":
            return arrays[x[1]]
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x

    return rec(template)


def encode_batch(tag: int, batch) -> bytes:
    template, arrays = _flatten(batch)
    tpl = pickle.dumps(template, protocol=pickle.HIGHEST_PROTOCOL)
    parts = [struct.pack("<III", tag, len(arrays), len(tpl)), tpl]
    for a in arrays:
        dt = a.dtype.str.encode()
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}Q" if a.ndim else "<0Q", *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_batch(data: bytes):
    tag, n, tpl_len = struct.unpack_from("<III", data, 0)
    off = 12
    template = pickle.loads(data[off:off + tpl_len])
    off += tpl_len
    arrays = []
    for _ in range(n):
        (dl,) = struct.unpack_from("<B", data, off)
        off += 1
        dt = np.dtype(data[off:off + dl].decode())
        off += dl
        (nd,) = struct.unpack_from("<B", data, off)
        off += 1
        shape = struct.unpack_from(f"<{nd}Q", data, off) if nd else ()
        off += 8 * nd
        (nb,) = struct.unpack_from("<Q", data, off)
        off += 8
        arrays.append(np.frombuffer(data, dtype=dt, count=nb // dt.itemsize,
                                    offset=off).reshape(shape))
        off += nb
    return tag, _unflatten(template, arrays)
