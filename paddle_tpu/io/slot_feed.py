"""Python face of the native dense-slot parser (csrc/slot_feed.cpp).

≙ reference framework/data_feed.cc MultiSlotDataFeed — C++ parses the
example files (a Python float() per value starves the device), Python
batches, XLA computes.  Used automatically by io.dataset.DatasetBase when
the default parser and format apply; importable directly for custom feeds.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from ..csrc import NativeBuildError, load_library


class _Lib:
    _lib = None
    _failed = False

    @classmethod
    def get(cls):
        if cls._lib is None and not cls._failed:
            try:
                lib = load_library("slot_feed")
                lib.slot_feed_dims.restype = ctypes.c_int
                lib.slot_feed_dims.argtypes = [
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64)]
                lib.slot_feed_parse.restype = ctypes.c_int64
                lib.slot_feed_parse.argtypes = [
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_longlong), ctypes.c_int64,
                    ctypes.c_int64, ctypes.c_int]
                cls._lib = lib
            except NativeBuildError:
                cls._failed = True  # no toolchain: callers fall back to python
        return cls._lib


def native_available() -> bool:
    return _Lib.get() is not None


def parse_dense_file(path: str, threads: int = 4
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a whitespace-separated numeric file whose last column is an int
    label.  Returns (feats float32 (N, C-1), labels int64 (N,)), or None if
    the native library is unavailable (caller falls back to Python parsing).
    Raises ValueError on malformed content (non-numeric tokens, short rows).
    """
    lib = _Lib.get()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.slot_feed_dims(path.encode(), ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise OSError(f"slot_feed_dims({path!r}) failed: errno {-rc}")
    n, c = rows.value, cols.value
    if n == 0 or c < 2:
        raise ValueError(f"{path}: need >=1 row and >=2 columns, got {n}x{c}")
    feats = np.empty((n, c - 1), np.float32)
    labels = np.empty((n,), np.int64)
    got = lib.slot_feed_parse(
        path.encode(), feats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        n, c, int(threads))
    if got < 0:
        raise ValueError(f"{path}: malformed slot file (code {got})")
    if got != n:
        raise ValueError(f"{path}: parsed {got} rows, expected {n}")
    return feats, labels
