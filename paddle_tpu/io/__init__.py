"""``paddle_tpu.io`` — Dataset/DataLoader (reference: python/paddle/io/,
fluid/reader.py:146 DataLoader, fluid/dataloader/).

TPU-first notes: the loader collates numpy on host workers and does an async
``jax.device_put`` prefetch of the next batch while the current step runs —
the equivalent of the reference's C++ BlockingQueue + buffered reader
(pybind/reader_py.cc) without a native queue, since XLA's async dispatch
already overlaps host→HBM copies with compute.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: List):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(getattr(t, "_data", t))[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self.cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * l) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(total)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic, int, float)):
        return np.stack([np.asarray(b) for b in batch])
    if isinstance(sample, Tensor):
        return np.stack([b.numpy() for b in batch])
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class _PrefetchIterator:
    """Background-thread loader with bounded queue (≙ reader_py.cc
    BlockingQueue + dataloader_iter.py _DataLoaderIterMultiProcess)."""

    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = index_iter
        self.q: "queue.Queue" = queue.Queue(maxsize=max(2, loader.prefetch_factor))
        self.done = object()
        self.error = None
        self.thread = threading.Thread(target=self._work, daemon=True)
        self.thread.start()

    def _work(self):
        try:
            for indices in self.index_iter:
                self.q.put(self.loader._fetch(indices))
        except BaseException as e:  # propagate to consumer
            self.error = e
        finally:
            self.q.put(self.done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is self.done:
            if self.error is not None:
                raise self.error
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last) if batch_size else None
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
        self.drop_last = drop_last

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        batch = self.collate_fn(samples)
        return self._to_tensors(batch)

    def _to_tensors(self, batch):
        if isinstance(batch, np.ndarray):
            return Tensor(jax.device_put(batch))
        if isinstance(batch, (list, tuple)):
            return [self._to_tensors(b) for b in batch]
        if isinstance(batch, dict):
            return {k: self._to_tensors(v) for k, v in batch.items()}
        if isinstance(batch, Tensor):
            return batch
        return Tensor(jax.device_put(np.asarray(batch)))

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        index_iter = iter(self.batch_sampler)
        if self.num_workers > 0 or self.use_buffer_reader:
            return _PrefetchIterator(self, index_iter)
        return (self._fetch(indices) for indices in index_iter)

    def _iter_iterable(self):
        it = iter(self.dataset)
        if self.batch_size is None:
            for sample in it:
                yield self._to_tensors(self.collate_fn([sample]))
            return
        while True:
            chunk = list(itertools.islice(it, self.batch_size))
            if not chunk:
                return
            if len(chunk) < self.batch_size and self.drop_last:
                return
            yield self._to_tensors(self.collate_fn(chunk))

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("length unavailable for IterableDataset loader")

    def __call__(self):
        return self.__iter__()
