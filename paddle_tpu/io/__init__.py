"""``paddle_tpu.io`` — Dataset/DataLoader (reference: python/paddle/io/,
fluid/reader.py:146 DataLoader, fluid/dataloader/).

TPU-first notes: with ``num_workers>0`` decode/collate runs in forked worker
processes (free of the parent GIL) and collated numpy batches travel through
the native shared-memory ring (csrc/shm_ring.cpp ≙ pybind/reader_py.cc
BlockingQueue + mmap_allocator.cc shared-mem tensors); a host-side pump
thread restores sampler order and ``jax.device_put``s the next batch while
the current step runs.  ``num_workers=0`` keeps the single prefetch thread.
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Tensor
from ..telemetry_ledger import current_ledger

logger = logging.getLogger(__name__)


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: List):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(getattr(t, "_data", t))[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self.cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * l) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(total)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic, int, float)):
        return np.stack([np.asarray(b) for b in batch])
    if isinstance(sample, Tensor):
        return np.stack([b.numpy() for b in batch])
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def _worker_main(worker_id, num_workers, dataset, collate_fn, worker_init_fn,
                 task_q, ring_name, ring_capacity, result_q, base_seed):
    """Worker-process loop (≙ dataloader_iter.py _worker_loop): pull index
    batches, decode/collate on this process's CPU, push the collated numpy
    batch through the shared-memory ring (or mp.Queue fallback)."""
    import numpy as _np
    _np.random.seed((base_seed + worker_id) % (2 ** 31))
    _worker_info.info = WorkerInfo(worker_id, num_workers, dataset, base_seed)
    out = None
    try:
        if ring_name is not None:
            from .shm_queue import ShmQueue
            out = ShmQueue(ring_name, ring_capacity, owner=False)

        def emit(tag, batch, error=False):
            if out is not None:
                from .shm_queue import encode_batch
                t = tag | (1 << 31) if error else tag
                out.put(encode_batch(t, batch))
            else:
                result_q.put((tag, batch, error))

        try:
            if worker_init_fn is not None:
                worker_init_fn(worker_id)
        except Exception as e:  # must reach the main process, not just stderr
            import traceback
            emit(0, {"error": f"worker_init_fn: {e}\n{traceback.format_exc()}"},
                 error=True)
            return
        for task in iter(task_q.get, None):
            tag, indices = task
            try:
                samples = [dataset[i] for i in indices]
                emit(tag, collate_fn(samples))
            except Exception as e:  # ship the failure to the main process
                import traceback
                emit(tag, {"error": f"{e}\n{traceback.format_exc()}"},
                     error=True)
    except (EOFError, KeyboardInterrupt):
        pass


class _MPResources:
    """Everything the pump thread and shutdown need, deliberately separate
    from the iterator object so the thread can hold it STRONGLY while holding
    the iterator only weakly — an abandoned iterator is then garbage
    collectable, the pump notices the dead weakref and releases the workers
    and the shm ring instead of leaking them."""

    def __init__(self, workers, tasks, ring, result_q, prefetch=2):
        self.workers = workers
        self.tasks = tasks
        self.ring = ring
        self.result_q = result_q
        self.closed = threading.Event()
        self.out_q: "queue.Queue" = queue.Queue(maxsize=max(2, prefetch))
        self._down = False

    def any_worker_dead(self):
        return any(not w.is_alive() and w.exitcode != 0 for w in self.workers)

    def shutdown(self):
        if self._down:
            return
        self._down = True
        self.closed.set()
        for _ in self.workers:
            try:
                self.tasks.put_nowait(None)
            except (queue.Full, ValueError, OSError) as e:
                # full task queue or a queue torn down under us — workers
                # also exit on the closed event, so dropping the sentinel
                # is safe; still worth a trace for hang forensics
                logger.debug("shutdown: task sentinel not enqueued: %s", e)
        if self.ring is not None:
            self.ring.close()
        for w in self.workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()


class _MultiprocessIterator:
    """Process-worker loader (≙ dataloader_iter.py:336 _DataLoaderIterMultiProcess).

    - fork workers decode/collate in parallel, free of the parent's GIL;
    - batches travel through the native shared-memory ring
      (csrc/shm_ring.cpp; mp.Queue fallback when the native build fails);
    - a host thread reorders by batch index (determinism contract: output
      order == sampler order regardless of worker timing) and device_puts
      the next batch while the consumer steps (double buffering);
    - ``loader.timeout`` bounds the wait for any single batch (0 = a
      liveness-checked indefinite wait); close()/GC release all resources.
    """

    def __init__(self, loader, index_iter):
        import multiprocessing as mp
        import uuid
        import weakref

        self.loader = loader
        # fork by default (workers inherit loaded modules — instant start, no
        # pickling requirement, torch-DataLoader-compatible UX for locally
        # defined datasets; workers only run numpy, never JAX).  Python 3.12
        # warns that forking a JAX-multithreaded parent can deadlock; the
        # alternative default (forkserver) breaks every locally-defined
        # dataset/collate_fn on pickling, which is the worse trade.  Set
        # PADDLE_TPU_WORKER_START=forkserver for fork-immunity when your
        # dataset is picklable (the suite's fallback test runs that path).
        method = os.environ.get("PADDLE_TPU_WORKER_START", "fork")
        ctx = mp.get_context(method)
        n = loader.num_workers
        tasks = ctx.Queue()
        ring, result_q, ring_name = None, None, None
        ring_cap = 128 << 20
        if loader.use_shared_memory:
            try:
                from .shm_queue import ShmQueue
                ring_name = f"/pt_dl_{os.getpid()}_{uuid.uuid4().hex[:8]}"
                ring = ShmQueue(ring_name, ring_cap, owner=True)
            except Exception:  # native build unavailable
                ring_name = None
        if ring_name is None:  # mp.Queue transport fallback
            result_q = ctx.Queue()
        base_seed = int(np.random.randint(0, 2 ** 31))
        workers = [
            ctx.Process(
                target=_worker_main,
                args=(i, n, loader.dataset, loader.collate_fn,
                      loader.worker_init_fn, tasks, ring_name, ring_cap,
                      result_q, base_seed),
                daemon=True)
            for i in range(n)]
        for w in workers:
            w.start()

        self._res = _MPResources(workers, tasks, ring, result_q,
                                 prefetch=loader.prefetch_factor)
        window = max(2, loader.prefetch_factor) * n
        timeout = float(loader.timeout) if loader.timeout else 0.0
        pump = threading.Thread(
            target=_mp_pump, daemon=True,
            args=(weakref.ref(self), self._res, index_iter, window,
                  loader._to_tensors, timeout))
        pump.start()

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        res = self._res
        # goodput seam: the consumer-blocked wait for the next batch is
        # data_wait (worker decode/collate and the pump's device_put are
        # overlapped — only the stall the training thread actually feels
        # counts).  One is-None check when no ledger is active.
        led = current_ledger()
        t0 = time.perf_counter() if led is not None else 0.0
        while True:
            try:
                item = res.out_q.get(timeout=1.0)
                break
            except queue.Empty:
                if res.closed.is_set():
                    raise StopIteration
        if led is not None:
            led.record("data_wait", time.perf_counter() - t0)
        if item is _DONE:
            raise StopIteration
        if isinstance(item, _Err):
            raise item.e
        return item

    def close(self):
        self._res.shutdown()

    def __del__(self):
        try:
            self._res.shutdown()
        except (OSError, RuntimeError, AttributeError) as e:
            # GC during interpreter teardown: queues/threads may already be
            # gone (AttributeError on a half-built iterator, RuntimeError
            # from join); leak forensics want the debug line
            logger.debug("_MultiprocessIterator.__del__: shutdown failed: %s", e)


def _mp_pump(iter_ref, res, index_iter, window, to_tensors, timeout):
    """Pump-thread body.  Holds the iterator only via ``iter_ref`` so an
    abandoned iterator gets collected; on a dead ref (or close()) all
    resources are released."""
    next_tag = 0
    next_yield = 0
    reorder = {}
    more = True

    def dispatch():
        nonlocal next_tag, more
        while more and next_tag - next_yield < window:
            try:
                indices = next(index_iter)
            except StopIteration:
                more = False
                return
            res.tasks.put((next_tag, list(indices)))
            next_tag += 1

    def recv_one(deadline):
        while True:
            if res.closed.is_set() or iter_ref() is None:
                raise _Abandoned
            try:
                if res.ring is not None:
                    from .shm_queue import decode_batch
                    tag, batch = decode_batch(res.ring.get(timeout=1.0))
                    err = bool(tag & (1 << 31))
                    return tag & ~(1 << 31), batch, err
                return res.result_q.get(timeout=1.0)
            except (TimeoutError, queue.Empty):
                if res.any_worker_dead():
                    raise RuntimeError(
                        "DataLoader worker died without reporting an error "
                        "(killed or crashed in native code)")
                if deadline is not None and time.monotonic() > deadline:
                    raise RuntimeError(
                        f"DataLoader batch wait exceeded timeout={timeout}s")

    def put_out(item):
        while True:
            if res.closed.is_set() or iter_ref() is None:
                raise _Abandoned
            try:
                res.out_q.put(item, timeout=1.0)
                return
            except queue.Full:
                continue

    try:
        dispatch()
        while next_yield < next_tag:
            deadline = time.monotonic() + timeout if timeout else None
            while next_yield not in reorder:
                tag, batch, err = recv_one(deadline)
                if err:
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {tag}: "
                        f"{batch.get('error', batch)}")
                reorder[tag] = batch
            batch = reorder.pop(next_yield)
            next_yield += 1
            dispatch()
            # device transfer off the consumer thread (double buffer)
            put_out(to_tensors(batch))
        put_out(_DONE)
    except _Abandoned:
        pass
    except BaseException as e:
        try:
            put_out(_Err(e))
        except _Abandoned:
            pass
    finally:
        res.shutdown()


class _Abandoned(BaseException):
    pass


_DONE = object()


class _Err:
    def __init__(self, e):
        self.e = e


class _PrefetchIterator:
    """Background-thread loader with bounded queue (≙ reader_py.cc
    BlockingQueue + dataloader_iter.py _DataLoaderIterMultiProcess)."""

    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = index_iter
        self.q: "queue.Queue" = queue.Queue(maxsize=max(2, loader.prefetch_factor))
        self.done = object()
        self.error = None
        self.thread = threading.Thread(target=self._work, daemon=True)
        self.thread.start()

    def _work(self):
        try:
            for indices in self.index_iter:
                self.q.put(self.loader._fetch(indices))
        except BaseException as e:  # propagate to consumer
            self.error = e
        finally:
            self.q.put(self.done)

    def __iter__(self):
        return self

    def __next__(self):
        # goodput seam: consumer-blocked next-batch wait → data_wait
        led = current_ledger()
        if led is None:
            item = self.q.get()
        else:
            t0 = time.perf_counter()
            item = self.q.get()
            led.record("data_wait", time.perf_counter() - t0)
        if item is self.done:
            if self.error is not None:
                raise self.error
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.use_shared_memory = use_shared_memory
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last) if batch_size else None
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
        self.drop_last = drop_last

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        batch = self.collate_fn(samples)
        return self._to_tensors(batch)

    def _fetch_timed(self, indices):
        """Synchronous-path fetch with the goodput data_wait seam: with no
        prefetch thread, decode + collate + device_put all happen on the
        consumer thread and ARE the next-batch wait."""
        led = current_ledger()
        if led is None:
            return self._fetch(indices)
        with led.span("data_wait"):
            return self._fetch(indices)

    def _to_tensors(self, batch):
        if isinstance(batch, np.ndarray):
            return Tensor(jax.device_put(batch))
        if isinstance(batch, (list, tuple)):
            return [self._to_tensors(b) for b in batch]
        if isinstance(batch, dict):
            return {k: self._to_tensors(v) for k, v in batch.items()}
        if isinstance(batch, Tensor):
            return batch
        return Tensor(jax.device_put(np.asarray(batch)))

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        index_iter = iter(self.batch_sampler)
        if self.num_workers > 0:
            return _MultiprocessIterator(self, index_iter)
        if self.use_buffer_reader:
            return _PrefetchIterator(self, index_iter)
        return (self._fetch_timed(indices) for indices in index_iter)

    def _iter_iterable(self):
        def produce(samples):
            led = current_ledger()
            if led is None:
                return self._to_tensors(self.collate_fn(samples))
            with led.span("data_wait"):
                return self._to_tensors(self.collate_fn(samples))

        it = iter(self.dataset)
        if self.batch_size is None:
            for sample in it:
                yield produce([sample])
            return
        while True:
            chunk = list(itertools.islice(it, self.batch_size))
            if not chunk:
                return
            if len(chunk) < self.batch_size and self.drop_last:
                return
            yield produce(chunk)

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("length unavailable for IterableDataset loader")

    def __call__(self):
        return self.__iter__()


from .dataset import DatasetBase, InMemoryDataset, QueueDataset  # noqa: F401,E402
