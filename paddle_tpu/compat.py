"""Py2/3 compatibility shims (reference: python/paddle/compat.py).  Python 3
only here, so these are thin canonicalizers kept for API parity."""

from __future__ import annotations

__all__ = ["to_text", "to_bytes", "long_type", "floor_division",
           "get_exception_message", "round"]

long_type = int


def to_text(obj, encoding="utf-8", inplace=False):
    if obj is None:
        return None
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj).decode(encoding)
    if isinstance(obj, list):
        return [to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        return {to_text(o, encoding) for o in obj}
    if isinstance(obj, dict):
        return {to_text(k, encoding): to_text(v, encoding)
                for k, v in obj.items()}
    return str(obj)


def to_bytes(obj, encoding="utf-8", inplace=False):
    if obj is None:
        return None
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, list):
        return [to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        return {to_bytes(o, encoding) for o in obj}
    if isinstance(obj, dict):
        return {to_bytes(k, encoding): to_bytes(v, encoding)
                for k, v in obj.items()}
    return bytes(obj)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)


def round(x, d=0):
    import builtins
    return builtins.round(x, d)
