"""Paged (block-table) KV cache for the continuous-batching engine.

The contiguous engine reserves ``max_slots × max_len`` cache positions
regardless of actual request lengths — one long request dictates every
slot's allocation, and ``ticks_per_sync`` strands up to k−1 positions per
retirement (serving.py documents the waste).  This module replaces the
per-slot rows with the vLLM/"ragged paged attention" discipline (PAPERS.md),
re-shaped for XLA's static-shape model:

- ONE physical pool of ``num_blocks`` fixed-size blocks per layer,
  ``(L, num_blocks + 1, block_size, nh, hd)`` — block 0 is a reserved TRASH
  block that absorbs inactive slots' parked stale writes (never read);
- a per-slot BLOCK TABLE ``(S, max_len // block_size)`` int32 mapping
  logical positions to pool blocks.  The table is a **traced operand**, not
  a program constant: allocation patterns never recompile — decode compiles
  one program per power-of-two LENGTH BUCKET (≤ log2(max_len/block_size)
  programs; see _decode_prog_all), prefill one per prompt bucket;
- blocks are allocated LAZILY, right before each decode sync, so persistent
  HBM scales with tokens actually resident, admission is independent of
  ``max_new_tokens``, and retirement frees every block immediately;
- when the pool runs dry mid-decode the YOUNGEST request is preempted
  (blocks freed, request requeued at the front and rerun from scratch —
  greedy decoding regenerates the identical prefix, so outputs stay
  oracle-exact; streaming callbacks see the replayed tokens again).

Device-side the engine stays a pure serving-layer construct: the decode
program wraps the pool + (length-bucketed, inactive-zeroed) table as a
``PagedKV`` pytree and runs the exact same shared tick as the contiguous
engine — decode_step's layer scan slices pool and table together,
``write_cache`` scatters straight into pool blocks, and
``cached_attention`` densifies ONE layer's table-selected blocks at a
time (a transient ``(S, C·block_size, nh, hd)`` view per layer, where C
is the smallest power-of-two block count covering the deepest active
clock; there is no all-layer view and no scatter-back pass).  The
gather/scatter pattern survives only in the single-slot prefill/segment
programs.  Collapsing the per-layer transient entirely needs a Pallas
paged-attention kernel that walks the table in-kernel (the PAPERS.md
design), the designated TPU hot-path follow-up.

No reference counterpart: the reference snapshot serves static batches only
(SURVEY §2.3); paged serving is beyond-reference capability.
"""

from __future__ import annotations

import collections
import logging
import math
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .serving import ContinuousBatchingEngine, _default_buckets
from .jit.bucketing import pow2_bucket, pow2_grid, select_bucket
from .kv_store import KVPage, chain_hex
from .models._decode import (PagedKV, apply_repetition_penalty,
                             greedy_verify, seed_presence, suppress_eos,
                             suppress_eos_rows)

__all__ = ["PagedContinuousBatchingEngine",
           "PagedSpeculativeBatchingEngine",
           "RaggedPagedContinuousBatchingEngine",
           "SpeculativeBatchingEngine"]


# ---------------------------------------------------------------------------
# KV-page transport: pool block <-> host page (paddle_tpu/kv_store.py)
# ---------------------------------------------------------------------------
# ONE compiled program per pool-leaf signature for ALL block ids (the id
# is a traced operand, never a static index) — tiering/migration adds a
# fixed pair of tiny programs per engine config, zero per-block families.
# Module-level jit: these live OUTSIDE the engines' program caches, so
# engine compile counters (the zero-in-serve-compile pins) are untouched;
# the kvio warmup task pre-compiles them for warmed engines.

@partial(jax.jit, donate_argnums=(0,))
def _kv_block_put(pool, block, bid):
    """Write one block's content at pool[:, bid] (pool donated — the
    update is in place, no transient pool copy)."""
    return jax.lax.dynamic_update_slice_in_dim(
        pool, block[:, None].astype(pool.dtype), bid, axis=1)


@jax.jit
def _kv_block_get(pool, bid):
    """Read one block's content pool[:, bid] (device-side; the caller
    batches the host fetch across leaves)."""
    return jax.lax.dynamic_slice_in_dim(pool, bid, 1, axis=1)[:, 0]


class PagedContinuousBatchingEngine(ContinuousBatchingEngine):
    """Continuous batching over a paged KV cache (see module docstring).

    ``block_size`` must divide ``max_len`` and every prompt bucket.
    ``num_blocks`` defaults to the contiguous-equivalent pool
    (``max_slots × max_len / block_size``); size it smaller to cap HBM —
    the engine then admits/preempts against the real budget.
    """

    def __init__(self, model, params, max_slots: int, max_len: int,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 enable_prefix_cache: bool = False, kv_store=None, **kw):
        if kw.get("mesh") is not None:
            raise NotImplementedError(
                "paged engine v1 is single-mesh (TP serving uses the "
                "contiguous engine)")
        self.prefix_caching = bool(enable_prefix_cache)
        # tiered page store (paddle_tpu/kv_store.py): prefix-cache
        # eviction DEMOTES pages into it instead of dropping, and
        # admission lookups that miss HBM RESTORE from it device-side —
        # host-side only, program signatures identical with or without
        if kv_store is not None and not self.prefix_caching:
            raise ValueError(
                "kv_store needs enable_prefix_cache=True — pages are "
                "addressed by prefix-cache chain digests")
        self.kv_store = kv_store
        self._kv_meta = None           # kv_page_meta() computes it once
        self.bs = int(block_size)
        if self.bs < 1:
            raise ValueError("block_size must be >= 1")
        if max_len % self.bs:
            raise ValueError(f"block_size ({self.bs}) must divide "
                             f"max_len ({max_len})")
        self.MB = max_len // self.bs
        self.NB = (int(num_blocks) if num_blocks is not None
                   else int(max_slots) * self.MB)
        if self.NB < 1:
            raise ValueError("num_blocks must be >= 1")
        super().__init__(model, params, max_slots, max_len, **kw)
        bad = [b for b in self.buckets if b % self.bs]
        if bad:
            raise ValueError(f"block_size ({self.bs}) must divide every "
                             f"prompt bucket; doesn't divide {bad}")
        # block 0 is trash; real ids are 1..NB
        self._free = list(range(self.NB, 0, -1))      # pop() -> 1, 2, …
        self._table = np.zeros((self.S, self.MB), np.int32)
        self._nblk = np.zeros(self.S, np.int32)       # leading real blocks
        self._admit_seq = np.zeros(self.S, np.int64)  # preemption (LIFO)
        self._seq = 0
        # prefix cache: a block is free / referenced (refs > 0) / CACHED
        # (refs == 0 but registered under its content chain — evictable).
        # Chain key = (pad, padded prompt tokens through this block): the
        # pad length shifts logical positions, so identical token blocks at
        # different pads have different k/v and must not collide.
        self._refs = {}                               # bid -> refcount
        self._prefix_cache = collections.OrderedDict()  # chain -> bid (LRU)
        self._key_of = {}                             # bid -> chain
        # allocator counters live in the per-engine registry (serving.py
        # builds it) so metrics()/prometheus/tick deltas share one source;
        # the public names below stay readable attributes via properties

    _TICK_COUNTERS = (ContinuousBatchingEngine._TICK_COUNTERS
                      + ("blocks_allocated", "blocks_released",
                         "preemptions", "prefix_hits"))

    @property
    def preemptions(self) -> int:
        return int(self._stats.value("preemptions"))

    @property
    def prefix_hits(self) -> int:
        return int(self._stats.value("prefix_hits"))

    @property
    def prefix_blocks_reused(self) -> int:
        return int(self._stats.value("prefix_blocks_reused"))

    @property
    def blocks_high_water(self) -> int:
        return int(self._stats.value("blocks_high_water"))

    def _tick_gauges(self):
        return {"blocks_in_use": self.blocks_in_use}

    # ------------------------------------------------------------ storage --

    def _build_pool(self, c):
        """Block pools for one model config (the paged-speculative
        composition builds a second pool for the draft — SAME allocator
        and tables, different pool storage)."""
        nh = c.num_attention_heads
        hd = c.hidden_size // nh
        shape = (c.num_layers, self.NB + 1, self.bs, nh, hd)
        if getattr(c, "kv_cache_dtype", None) == "int8":
            def one():
                return (jnp.zeros(shape, jnp.int8),
                        jnp.zeros(shape[:-1], jnp.float32))
            return one(), one()
        dt = jnp.dtype(c.compute_dtype)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def _alloc_caches(self):
        return self._build_pool(self.model.config)

    def _paged_sig_suffix(self):
        from .core.flags import flag
        # the kernel-dispatch flags are baked into compiled programs at
        # trace time — key them so set_flags() takes effect on the next
        # program fetch instead of being silently ignored.  ONE helper for
        # every paged signature (the spec composition included): a flag
        # added here reaches all of them
        return ("paged", self.bs, self.NB,
                bool(flag("FLAGS_use_pallas_kernels")),
                bool(flag("FLAGS_paged_attn_interpret")))

    @property
    def _sig(self):
        return (ContinuousBatchingEngine._sig.fget(self)
                + self._paged_sig_suffix())

    # --------------------------------------------------------- allocator --

    @property
    def blocks_in_use(self) -> int:
        return self.NB - len(self._free)

    def _evictable_count(self) -> int:
        """Cached prefix blocks with no live pins — allocatable on demand
        (ONE definition for the allocator, metrics, and the ragged pack
        builder)."""
        return sum(1 for b in self._prefix_cache.values()
                   if self._refs.get(b, 0) == 0)

    def _alloc_blocks(self, n: int):
        """Take ``n`` fresh blocks (refs = 1 each) from the free list,
        evicting least-recently-used UNREFERENCED cached blocks as needed.
        TRANSACTIONAL: returns None (nothing taken) when free + evictable
        can't cover ``n`` — partial growth on a slot that then isn't
        admitted would strand blocks outside every tracked set and
        livelock the preemption loop."""
        if n <= 0:
            return []
        evictable = [c for c, b in self._prefix_cache.items()
                     if self._refs.get(b, 0) == 0]
        if n > len(self._free) + len(evictable):
            return None
        out = []
        ev = iter(evictable)                      # LRU-first (OrderedDict)
        while len(out) < n:
            if self._free:
                out.append(self._free.pop())
            else:
                chain = next(ev)
                bid = self._prefix_cache.pop(chain)
                del self._key_of[bid]
                if self.kv_store is not None:
                    # eviction DEMOTES instead of dropping: the page
                    # moves down the tier ladder (HBM -> DRAM -> disk)
                    self._demote_page(chain, bid)
                out.append(bid)
        for bid in out:
            self._refs[bid] = 1
        self._stats.add("blocks_allocated", len(out))
        return out

    def _pin(self, bid: int):
        """Take one reference on a cached prefix block.  A 0→1 pin is
        allocator TRAFFIC — the block leaves the evictable set — and
        counts ``blocks_allocated``, mirroring ``_release``'s count at
        1→0: ``blocks_allocated == blocks_released`` holds at quiescence
        with prefix hits and cancels interleaved (the fuzz pins it)."""
        self._refs[bid] += 1
        if self._refs[bid] == 1:
            self._stats.add("blocks_allocated")

    def _release(self, bid: int):
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            self._stats.add("blocks_released")    # unpinned (maybe cached)
            if bid not in self._key_of:
                self._free.append(bid)            # cached blocks linger

    def _ensure_blocks(self, slot: int, upto: int) -> bool:
        """Grow the slot's table to cover logical positions [0, upto);
        transactional via _alloc_blocks."""
        need = -(-int(upto) // self.bs)
        have = int(self._nblk[slot])
        got = self._alloc_blocks(need - have)
        if got is None:
            return False
        for i, bid in enumerate(got):
            self._table[slot, have + i] = bid
        self._nblk[slot] = max(have, need)
        self._stats.set("blocks_high_water", max(self.blocks_high_water,
                                                 self.blocks_in_use))
        return True

    def _free_slot_blocks(self, slot: int):
        n = int(self._nblk[slot])
        for b in self._table[slot, :n][::-1]:
            self._release(int(b))
        self._table[slot] = 0
        self._nblk[slot] = 0

    # ------------------------------------------------------ prefix cache --

    def _chain_keys(self, ids, pad, nblocks):
        """The chain key for each of the first ``nblocks`` prompt blocks:
        a ROLLING blake2b-256 over (pad, tokens through block i).
        O(1)-sized keys and O(P) total work per admission — nested token
        tuples would make every dict operation on the TTFT path re-hash
        the whole prefix (O(P^2) per admission).  blake2b rather than
        sha1: prompt tokens are attacker-controlled in a shared
        multi-tenant cache, and a chosen-prefix sha1 collision would
        silently map one tenant's cached k/v blocks into another's
        attention context (ADVICE r5)."""
        import hashlib

        def h(data):
            return hashlib.blake2b(data, digest_size=32).digest()

        out = []
        digest = h(str(pad).encode())
        for i in range(nblocks):
            block = np.asarray(ids[i * self.bs:(i + 1) * self.bs],
                               np.int64).tobytes()
            digest = h(digest + block)
            out.append(digest)
        return out

    def _lookup_prefix(self, ids, pad, P):
        """Longest cached chain of FULL prompt blocks, capped at
        P/bs - 1 so the last prompt block is always recomputed (its
        forward pass yields the first-token hidden state for free).

        With a ``kv_store`` attached, a chain that misses HBM but hits a
        lower tier (host DRAM / disk) is RESTORED device-side right here
        — before any fill tick — so the caller sees it as a plain HBM
        hit and the admitted request's stream is token-identical to the
        cold-recompute oracle (tests/test_kv_store.py pins it)."""
        chains = self._chain_keys(ids, pad, P // self.bs - 1)
        F, bids = 0, []
        if self.kv_store is None:
            for chain in chains:
                bid = self._prefix_cache.get(chain)
                if bid is None:
                    break
                self._prefix_cache.move_to_end(chain)     # LRU touch
                bids.append(bid)
                F += 1
            return F, bids
        # store-aware walk: a restore ALLOCATES a block, and allocation
        # can evict other refs-0 cached blocks — TEMP-PIN every matched
        # block for the walk's duration so a later restore can never
        # evict an earlier match out from under the caller.  The pins
        # are released before returning (the caller re-pins immediately,
        # single-threaded), and each 0->1/1->0 pair counts allocator
        # traffic symmetrically — blocks_allocated == blocks_released
        # still holds at quiescence (the fuzz pins it).
        held = []
        try:
            for chain in chains:
                bid = self._prefix_cache.get(chain)
                if bid is not None:
                    self._prefix_cache.move_to_end(chain)     # LRU touch
                    self._pin(bid)
                else:
                    bid = self._restore_page(chain)
                    if bid is None:
                        break
                held.append(bid)
                bids.append(bid)
                F += 1
        finally:
            for bid in held:
                self._release(bid)
        return F, bids

    # ---------------------------------------------------- tiered kv store --

    def attach_kv_store(self, store):
        """Attach (or with None detach) a
        :class:`~paddle_tpu.kv_store.TieredKVStore`: prefix-cache
        eviction demotes pages into it, admission lookups restore from
        it, and the gateway's migration path delivers cross-replica
        pages through it (docs/KV_TIERING.md)."""
        if store is not None and not self.prefix_caching:
            raise ValueError(
                "kv_store needs enable_prefix_cache=True — pages are "
                "addressed by prefix-cache chain digests")
        self.kv_store = store
        return store

    def kv_page_meta(self):
        """Portable page signature (JSON-able): block size, the PROMPT
        BUCKET ladder, and each pool leaf's dtype and per-block shape —
        int8 pools list their fp32 scale planes as just another leaf.
        The bucket ladder matters: chain digests are seeded with the
        bucket-dependent pad, so engines with different ladders derive
        DIFFERENT chains for the same prompt — their pages would never
        restore; carrying the ladder makes the migration dest-picker
        reject the mismatch up front and fall back cleanly.  Two
        engines exchange pages iff their metas match.  Computed ONCE
        (it is a constant of the engine config): restores sit on the
        TTFT-critical admission path, one tree-flatten per block would
        tax exactly what the tier speeds up."""
        if self._kv_meta is None:
            leaves, _ = jax.tree.flatten(self.caches)
            self._kv_meta = ["kv1", self.bs, list(self.buckets),
                             [[str(leaf.dtype),
                               [int(leaf.shape[0])]
                               + [int(s) for s in leaf.shape[2:]]]
                              for leaf in leaves]]
        return self._kv_meta

    def _gather_page(self, bid: int):
        """One block's k/v for every pool leaf, device -> host (one
        batched fetch, not one sync per leaf)."""
        leaves, _ = jax.tree.flatten(self.caches)
        vals = [_kv_block_get(leaf, jnp.int32(bid)) for leaf in leaves]
        return tuple(jax.device_get(vals))

    def _scatter_page(self, bid: int, payload):
        """Write one page's leaves into pool block ``bid`` (donated
        in-place updates; ONE fixed program per leaf signature)."""
        leaves, treedef = jax.tree.flatten(self.caches)
        new = [_kv_block_put(leaf, jnp.asarray(arr), jnp.int32(bid))
               for leaf, arr in zip(leaves, payload)]
        self.caches = jax.tree.unflatten(treedef, new)

    def _demote_page(self, chain, bid: int):
        """Move one evicted block's content into the attached store (ONE
        host sync per demotion — the explicit price of keeping the page
        instead of dropping it).  A failing store degrades to the
        pre-store behaviour (page dropped, recompute stays correct)."""
        try:
            page = KVPage(chain, self._gather_page(bid),
                          self.kv_page_meta())
            self.kv_store.put(page)
        except Exception:  # noqa: BLE001 — a broken store must never
            # take the allocator down; dropping the page is always safe
            logging.getLogger(__name__).exception(
                "kv_store demotion failed; page dropped")
            return
        self._stats.add("kvstore_demoted_blocks")
        if self.tracer is not None:
            self.tracer.emit("kvstore", what="demote",
                             chain=chain_hex(chain)[:16],
                             bytes=page.nbytes,
                             engine=type(self).__name__)

    def _restore_page(self, chain) -> Optional[int]:
        """Restore one lower-tier page into a freshly allocated HBM
        block; returns the block id (held at refs=1 by the allocation —
        the caller releases) or None on a store miss / dry pool."""
        page = self.kv_store.lookup(chain, meta=self.kv_page_meta())
        if page is None or isinstance(page.payload, bytes):
            return None
        got = self._alloc_blocks(1)
        if got is None:
            return None          # pool dry: the page stays in the store
        bid = got[0]
        self._scatter_page(bid, page.payload)
        self._prefix_cache[chain] = bid
        self._key_of[bid] = chain
        self._stats.add("kvstore_restored_blocks")
        if self.tracer is not None:
            self.tracer.emit("kvstore", what="restore",
                             chain=chain_hex(chain)[:16],
                             bytes=page.nbytes,
                             engine=type(self).__name__)
        return bid

    def flush_prefix(self) -> int:
        """Demote every UNREFERENCED cached prefix block to the attached
        store and free it from HBM — the operator / bench primitive
        behind the warm-lower-tier A/B (``gpt_kv_tier``) and the smoke
        gate's demote→evict→restore round trip.  Pinned blocks (live
        requests) stay.  Returns the demoted block count."""
        if self.kv_store is None:
            raise ValueError("flush_prefix needs an attached kv_store")
        n = 0
        for chain, bid in list(self._prefix_cache.items()):
            if self._refs.get(bid, 0) != 0:
                continue                   # pinned by a live request
            self._prefix_cache.pop(chain)
            del self._key_of[bid]
            self._demote_page(chain, bid)
            self._free.append(bid)
            n += 1
        return n

    def export_prefix_pages(self, prompt) -> list:
        """The migration source's primitive: the prompt's resident KV
        pages (the bucket's first ``P/bs - 1`` blocks, chain order —
        the cap ``_lookup_prefix`` restores to; the last bucket block is
        always recomputed by the consumer, so its page would only burn
        transfer budget and destination DRAM) as portable
        :class:`~paddle_tpu.kv_store.KVPage` objects.  Walks HBM first,
        then the attached store; stops at the first miss (pages past a
        hole are unreachable by the chain walk anyway).  Empty when
        prefix caching is off or nothing is resident."""
        if not self.prefix_caching:
            return []
        prompt = [int(t) for t in prompt]
        if not prompt:
            return []
        try:
            P = select_bucket(len(prompt), self.buckets)
        except ValueError:
            return []
        pad = P - len(prompt)
        ids = [0] * pad + prompt
        meta = self.kv_page_meta()
        pages = []
        for chain in self._chain_keys(ids, pad,
                                      max(P // self.bs - 1, 0)):
            bid = self._prefix_cache.get(chain)
            if bid is not None:
                pages.append(KVPage(chain, self._gather_page(bid), meta))
                continue
            if self.kv_store is not None:
                page = self.kv_store.lookup(chain, meta=meta)
                if page is not None:
                    pages.append(page)
                    continue
            break
        return pages

    def prefix_index(self):
        """PUBLIC tier map ``{chain_hex: tier}`` (serving.py contract):
        HBM-resident chains first, the attached store's DRAM/disk tiers
        merged under them."""
        idx = {chain_hex(c): "hbm" for c in self._prefix_cache}
        if self.kv_store is not None:
            for c, tier in self.kv_store.index().items():
                idx.setdefault(chain_hex(c), tier)
        return idx

    def prefix_match(self, prompt):
        """PUBLIC tier-aware affinity read (serving.py contract): pure —
        no LRU touch, no pin, no restore.  ``hbm`` counts the leading
        blocks already device-resident; ``total`` counts leading blocks
        resident in ANY tier (a restore away from warm)."""
        out = {"hbm": 0, "total": 0, "tiers": []}
        if not self.prefix_caching:
            return out
        prompt = [int(t) for t in prompt]
        if not prompt:
            return out
        try:
            P = select_bucket(len(prompt), self.buckets)
        except ValueError:
            return out
        pad = P - len(prompt)
        ids = [0] * pad + prompt
        leading_hbm = True
        for chain in self._chain_keys(ids, pad,
                                      max(P // self.bs - 1, 0)):
            if chain in self._prefix_cache:
                tier = "hbm"
            else:
                tier = (self.kv_store.tier_of(chain)
                        if self.kv_store is not None else None)
                if tier is None:
                    break
            if tier != "hbm":
                leading_hbm = False
            if leading_hbm:
                out["hbm"] += 1
            out["total"] += 1
            out["tiers"].append(tier)
        return out

    def _warmup_kvio(self):
        """Compile the page gather/scatter programs (one fixed pair per
        pool-leaf signature, module-level jit cache — NOT engine program
        families): a round trip through the TRASH block, which is never
        read, writing back the very bytes just gathered — live state is
        value-identical.  Warmed engines restore/migrate pages with zero
        in-serve compiles."""
        self._scatter_page(0, self._gather_page(0))

    def _register_prompt_blocks(self, slot, ids, pad, P):
        """Publish the slot's (now content-final) prompt blocks into the
        prefix cache.  Prompt blocks are immutable from here on: buckets
        are block-aligned, so decode growth starts in a FRESH block and
        never writes inside [0, P) — sharing needs no copy-on-write.
        First writer wins on races (a loser's block stays private)."""
        if not self.prefix_caching:
            return
        for i, chain in enumerate(self._chain_keys(ids, pad,
                                                   P // self.bs)):
            bid = int(self._table[slot, i])
            if chain not in self._prefix_cache and \
                    bid not in self._key_of:
                self._prefix_cache[chain] = bid
                self._key_of[bid] = chain

    def _retire(self, slot: int):
        super()._retire(slot)
        self._free_slot_blocks(slot)

    def _release_cancelled_slot(self, slot: int):
        """Cancel's resource seam: release the slot's blocks exactly as
        retirement would — decode growth frees outright, cached prompt
        blocks drop their pin (refcount) and linger evictable, so
        ``blocks_allocated == blocks_released`` holds at quiescence with
        cancels interleaved (the allocator fuzz pins it)."""
        self._free_slot_blocks(slot)
        super()._release_cancelled_slot(slot)

    def _preempt_one(self) -> bool:
        """Evict the YOUNGEST in-flight request (active or still filling),
        free its blocks, and requeue it at the front for a from-scratch
        rerun.  Greedy decoding regenerates the identical prefix, so the
        exactness contract holds; sampled runs redraw from the engine key.

        Streaming consumers see the replayed prefix again: before the
        rerun, ``on_token(request_id, None, False)`` is invoked once as
        the documented replay/reset signal (``token is None`` == discard
        everything streamed for this request so far; see add_request)."""
        cands = [(int(self._admit_seq[s]), s)
                 for s in np.flatnonzero(self._active)]
        cands += [(int(self._admit_seq[s]), s) for s in self._filling]
        if not cands:
            return False
        _, victim = max(cands)
        if victim in self._filling:
            req = self._filling.pop(victim)["req"]
        else:
            req = self._slot_req[victim]
            self._slot_req[victim] = None
            self._active[victim] = False
        req.generated = []
        req.first_token_at = None
        self._queue.insert(0, req)
        self._free_slot_blocks(victim)
        self._stats.add("preemptions")
        if self.tracer is not None:
            self.tracer.request_event(req.id, "preempted",
                                      slot=int(victim))
        if req.on_token is not None:
            try:
                req.on_token(req.id, None, False)      # replay/reset signal
            except Exception:  # noqa: BLE001 — same contract as _record:
                # a user callback must not desync the scheduler
                logging.getLogger(__name__).exception(
                    "on_token replay signal failed for request %d", req.id)
        return True

    # ---------------------------------------------------------- programs --

    def _build_prefill(self, P: int):
        model = self.model
        track = self._track
        V = model.config.vocab_size
        tail = self._first_token_tail()
        bs = self.bs
        nblk = P // bs

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def run(params, pool_ck, pool_cv, ids, pad_len, blkrow, key,
                presence, slot, planes):
            h, (ck, cv) = model.prefill(params, ids, P,
                                        pad_lens=pad_len[None])

            def put(pool, new):                      # new: (L, 1, P, …)
                r = new.reshape((new.shape[0], nblk, bs) + new.shape[3:])
                return pool.at[:, blkrow].set(r.astype(pool.dtype))

            pool_ck = jax.tree.map(put, pool_ck, ck)
            pool_cv = jax.tree.map(put, pool_cv, cv)
            if track:
                row = seed_presence(ids, V, pad_len[None])
                presence = jax.lax.dynamic_update_slice(
                    presence, row, (slot, 0))
            tok, presence = tail(params, h[:, -1:], presence, slot, key,
                                 planes)
            return pool_ck, pool_cv, tok, presence

        return run

    def _build_seg(self, seg: int, first: bool, last: bool):
        model = self.model
        track = self._track
        V = model.config.vocab_size
        tail = self._first_token_tail()
        bs = self.bs
        suffix_prefill = self._suffix_prefill

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def run(params, pool_ck, pool_cv, toks, t0, pad, slot, presence,
                key, tabrow, planes):
            h, (pool_ck, pool_cv) = suffix_prefill(
                model, params, (pool_ck, pool_cv), toks, t0, pad, tabrow,
                bs)

            if track:
                if first:
                    presence = jax.lax.dynamic_update_slice(
                        presence, jnp.zeros((1, V), bool), (slot, 0))
                valid = t0 + jnp.arange(seg) >= pad
                row = presence[slot].at[toks[0]].max(valid)
                presence = jax.lax.dynamic_update_slice(
                    presence, row[None], (slot, 0))
            tok = jnp.int32(0)
            if last:
                tok, presence = tail(params, h[:, -1:], presence, slot, key,
                                 planes)
            return pool_ck, pool_cv, tok, presence

        return run

    def _cached_prefill_prog(self, P: int, F: int):
        return self._cached_prog(("cpre", P, F, self._sig),
                                 lambda: self._build_cached_prefill(P, F))

    @staticmethod
    def _suffix_prefill(m, prm, pools, toks, t0, pad, tabrow, bs):
        """ONE model's chunk prefill over its pools: gather the slot's
        table view, embed+decode the ``toks`` (1, n) chunk at positions
        [t0, t0+n) through the chunk path (attending to everything the
        table already holds), scatter the span back.  ``t0`` may be a
        TRACED scalar (segment programs reuse one compilation across
        positions) or static (cached-prefill suffixes).  Shared by the
        plain and speculative cached-prefill AND segment programs so the
        mechanics cannot drift."""
        def take(p):
            g = p[:, tabrow]
            g = g.reshape((g.shape[0], g.shape[1] * g.shape[2])
                          + g.shape[3:])
            return g[:, None]

        ck_s = jax.tree.map(take, pools[0])
        cv_s = jax.tree.map(take, pools[1])
        h = m._embed_chunk(prm, toks[0], t0, pad_lens=pad[None])
        h, (ck_s, cv_s) = m.decode_step(prm, h, (ck_s, cv_s), t0,
                                        pad_lens=pad[None])
        span = t0 + jnp.arange(toks.shape[1])
        pb = tabrow[jnp.minimum(span // bs, tabrow.shape[0] - 1)]
        off = span % bs

        def put(pool, v):
            chunk = v[:, 0, span]
            return pool.at[:, pb, off].set(chunk.astype(pool.dtype))
        return h, (jax.tree.map(put, pools[0], ck_s),
                   jax.tree.map(put, pools[1], cv_s))

    def _build_cached_prefill(self, P: int, F: int):
        """Admission prefill with the first F blocks already cached: embed
        and run ONLY the suffix [F·bs, P) through the chunk-decode path,
        attending to the shared prefix k/v through the slot's table; the
        suffix's last position yields the first-token hidden state.  One
        program per (bucket, F) — the program count stays bounded by
        sum over buckets of P/bs."""
        model = self.model
        track = self._track
        V = model.config.vocab_size
        tail = self._first_token_tail()
        bs = self.bs
        t0 = F * bs
        suffix_prefill = self._suffix_prefill

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def run(params, pool_ck, pool_cv, ids, pad, tabrow, key, presence,
                slot, planes):
            h, (pool_ck, pool_cv) = suffix_prefill(
                model, params, (pool_ck, pool_cv), ids[:, t0:], t0, pad,
                tabrow, bs)
            if track:
                # the presence row seeds from the FULL prompt — shared
                # prefix tokens count for the repetition penalty too
                row = seed_presence(ids, V, pad[None])
                presence = jax.lax.dynamic_update_slice(
                    presence, row, (slot, 0))
            tok, presence = tail(params, h[:, -1:], presence, slot, key,
                                 planes)
            return pool_ck, pool_cv, tok, presence

        return run

    def _decode_prog_all(self):
        """Decode programs are LENGTH-BUCKETED: each sync gathers only the
        first C table columns — the smallest power-of-two cover of the
        deepest active clock — so the transient view AND the attention
        width scale with actual sequence length, not max_len.  At most
        log2(MB) compiled decode programs."""
        C = self._view_cols()
        return self._cached_prog(("decode", C, self._sig),
                                 lambda: self._build_decode_cols(C))

    def _view_cols(self) -> int:
        k = self.ticks_per_sync
        # active clocks only: parked fillers sit at max_len - k by design
        # and must not inflate the bucket (their writes land in trash
        # regardless of C — the table's parked columns are 0 there)
        ts = self._t[self._active] if self._active.any() else [0]
        need = -(-int(max(ts) + k) // self.bs)
        return pow2_bucket(need, self.MB)

    def _build_decode_cols(self, C: int):
        k_ticks = self.ticks_per_sync
        tick = self._make_decode_tick()
        L = self.model.config.num_layers

        @partial(jax.jit, donate_argnums=(1, 2, 9))
        def run(params, pool_ck, pool_cv, table, toks, ts, pads, active,
                key, presence, emitted0, planes):
            # C table columns cover every active row (host-chosen bucket);
            # INACTIVE rows are pre-zeroed so their parked-clock writes —
            # whose clamped column lookup could alias a filling prompt's
            # real block — land in the trash block instead
            tb = jnp.where(active[:, None], table[:, :C], 0)
            tb = jnp.broadcast_to(tb[None], (L,) + tb.shape)
            pkv_ck = PagedKV(pool_ck, tb)
            pkv_cv = PagedKV(pool_cv, tb)
            # the pool flows through the SAME shared tick as the dense
            # engine: decode_step's layer scan slices pool+table together,
            # write_cache scatters straight into pool blocks, and
            # cached_attention densifies one layer at a time (transient
            # 1/L of the old pre-gathered view; no scatter-back pass)
            (pkv_ck, pkv_cv, _, _, presence), toks_out = jax.lax.scan(
                lambda c, i: tick(c, i, params, ts, pads, active, emitted0,
                                  planes),
                (pkv_ck, pkv_cv, toks, key, presence),
                jnp.arange(k_ticks))
            return pkv_ck.pool, pkv_cv.pool, toks_out, presence

        return run

    # --------------------------------------------------------- scheduling --

    def add_request(self, prompt, max_new_tokens: int, on_token=None,
                    trace_ctx=None, **sampling) -> int:
        """Queue a prompt (the base-engine contract, plus the paged
        engine's preemption semantics).  ``trace_ctx`` threads through to
        the base engine's tracer binding (end-to-end request tracing);
        a preempted request keeps its rid, so its replay events stay on
        the same trace span.

        PREEMPTION AND STREAMING: when the block pool runs dry the
        youngest in-flight request is preempted and rerun from scratch.
        An ``on_token`` consumer is told via a single
        ``on_token(request_id, None, False)`` call — ``token is None`` is
        the documented replay/reset signal: discard everything streamed
        for the request so far; the rerun re-delivers the stream from the
        first token.  Greedy (and deterministic per-request-config) rows
        regenerate the identical prefix; SAMPLED rows redraw from the
        engine key on replay, so a preempted sampling request's rerun is
        a different — still correctly distributed — stream.  Consumers
        needing replay-stable sampled streams should buffer until
        ``done`` or size ``num_blocks`` so preemption cannot occur."""
        prompt_l = [int(t) for t in prompt]
        if prompt_l:
            P = select_bucket(len(prompt_l), self.buckets)
            need = self._positions_needed(P, int(max_new_tokens))
            worst = -(-need // self.bs)
            # a request that exceeds max_len outright belongs to the base
            # validation (its error names the real limit); the pool guard
            # covers only requests the cache COULD hold
            if need <= self.max_len and worst > self.NB:
                raise ValueError(
                    f"request needs up to {worst} blocks; the pool has "
                    f"{self.NB} — raise num_blocks or lower "
                    f"max_new_tokens")
        return super().add_request(prompt_l, max_new_tokens,
                                   on_token=on_token, trace_ctx=trace_ctx,
                                   **sampling)

    def _admit(self):
        free = self._free_slots()
        while self._queue and free:
            slot = free[0]
            req = self._queue[0]
            P = select_bucket(len(req.prompt), self.buckets)
            pad = P - len(req.prompt)
            ids = [0] * pad + req.prompt
            chunked = (self.prefill_chunk is not None
                       and P > self.prefill_chunk)
            # prefix-cache path: map the cached chain, compute only the
            # suffix (which also bypasses chunking when the residual work
            # fits one chunk — the head-of-line cost IS the suffix)
            F, hit = (self._lookup_prefix(ids, pad, P)
                      if self.prefix_caching else (0, []))
            suffix = P - F * self.bs
            use_cached = F > 0 and (self.prefill_chunk is None
                                    or suffix <= self.prefill_chunk)
            if use_cached:
                for bid in hit:                   # pin before eviction runs
                    self._pin(bid)
                fresh = self._alloc_blocks(suffix // self.bs)
                if fresh is None:
                    for bid in hit:
                        self._release(bid)
                    break                          # defer admission (FIFO)
                free.pop(0)
                self._queue.pop(0)
                self._seq += 1
                self._admit_seq[slot] = self._seq
                self._table[slot, :F] = hit
                for i, bid in enumerate(fresh):
                    self._table[slot, F + i] = bid
                self._nblk[slot] = P // self.bs
                self._stats.set("blocks_high_water",
                                max(self.blocks_high_water,
                                    self.blocks_in_use))
                self._set_planes(slot, req)
                self._note("prefill_tokens", suffix)
                self._run_cached_prefill(slot, req, P, pad, ids, F)
                self._stats.add("prefix_hits")
                self._stats.add("prefix_blocks_reused", F)
                continue
            # whole-bucket admission needs its P/bs blocks NOW; chunked
            # admission grows per segment.  A dry pool defers admission
            # (FIFO preserved) — decoding slots retire and free blocks.
            if not chunked and not self._ensure_blocks(slot, P):
                break
            free.pop(0)
            self._queue.pop(0)
            self._seq += 1
            self._admit_seq[slot] = self._seq
            self._set_planes(slot, req)
            if chunked:
                # same clock-parking discipline as the contiguous engine;
                # the parked strip's table entry stays at trash (0) while
                # the slot fills, so stale decode writes land in trash
                self._t[slot] = self.max_len - self.ticks_per_sync
                self._filling[slot] = {"req": req, "ids": ids, "pad": pad,
                                       "P": P, "seg": 0,
                                       "nseg": P // self.prefill_chunk}
                continue
            self._note("prefill_tokens", P)
            self._run_admission_prefill(slot, req, P, pad, ids)

    def _run_cached_prefill(self, slot, req, P, pad, ids, F):
        """Prefix-hit admission: compute only the suffix (seam — the
        speculative composition fills BOTH pools' suffixes)."""
        run = self._cached_prefill_prog(P, F)
        ck, cv, tok0, self._presence = run(
            self.params, self.caches[0], self.caches[1],
            jnp.asarray([ids], jnp.int32), jnp.int32(pad),
            jnp.asarray(self._table[slot]), self._next_key(),
            self._presence, jnp.int32(slot), self._plane_operands())
        self.caches = (ck, cv)
        self._register_prompt_blocks(slot, ids, pad, P)
        self._activate(slot, req, P, pad, int(tok0))

    def _run_admission_prefill(self, slot, req, P, pad, ids):
        """Whole-bucket admission prefill for one slot (blocks already
        ensured).  The speculative composition overrides this with its
        dual-pool program; the scheduling loop above stays shared."""
        run = self._prefill_prog(P)
        blkrow = jnp.asarray(self._table[slot, :P // self.bs])
        ck, cv, tok0, self._presence = run(
            self.params, self.caches[0], self.caches[1],
            jnp.asarray([ids], jnp.int32), jnp.int32(pad), blkrow,
            self._next_key(), self._presence, jnp.int32(slot),
            self._plane_operands())
        self.caches = (ck, cv)
        self._register_prompt_blocks(slot, ids, pad, P)
        self._activate(slot, req, P, pad, int(tok0))

    def _fill_segments(self):
        seg = self.prefill_chunk
        for slot, st in list(self._filling.items()):
            if slot not in self._filling:      # preempted below mid-loop
                continue
            i, first = st["seg"], st["seg"] == 0
            last = i == st["nseg"] - 1
            if not self._ensure_blocks(slot, (i + 1) * seg):
                # pool dry: normally this prompt just stalls while decode
                # flows and retirements free blocks — but with NO active
                # decoder nothing will ever free them (fillers jointly
                # wedged); evict the youngest in-flight request so the
                # oldest filler is guaranteed to make progress
                if not self._active.any():
                    self._preempt_one()
                continue
            tok0 = self._run_fill_segment(slot, st, i, first, last)
            self._note("prefill_tokens", seg)
            if last:
                del self._filling[slot]
                self._register_prompt_blocks(slot, st["ids"], st["pad"],
                                             st["P"])
                # the ONLY host-device sync of the whole fill: non-last
                # segments return the device dummy unconverted so segment
                # programs pipeline under async dispatch
                self._activate(slot, st["req"], st["P"], st["pad"],
                               int(tok0))
            else:
                st["seg"] += 1

    def _run_fill_segment(self, slot, st, i, first, last):
        """Run ONE prefill segment's device program (seam — the
        speculative composition fills both pools).  Returns the
        first-token value as a DEVICE array (dummy 0 unless ``last``);
        the fill loop converts once at activation."""
        seg = self.prefill_chunk
        toks = jnp.asarray([st["ids"][i * seg:(i + 1) * seg]], jnp.int32)
        run = self._seg_prog(seg, first, last)
        ck, cv, tok0, self._presence = run(
            self.params, self.caches[0], self.caches[1], toks,
            jnp.int32(i * seg), jnp.int32(st["pad"]), jnp.int32(slot),
            self._presence, self._next_key(),
            jnp.asarray(self._table[slot]), self._plane_operands())
        self.caches = (ck, cv)
        return tok0                        # device value; caller converts

    def _prepare_decode(self) -> bool:
        k = self.ticks_per_sync
        # grow each active slot's table to cover this sync's [t, t+k) span,
        # OLDEST first (preemption victims are youngest-first, so the FIFO
        # head always makes progress — no livelock)
        order = sorted(np.flatnonzero(self._active),
                       key=lambda s: int(self._admit_seq[s]))
        for slot in order:
            while (self._active[slot]
                   and not self._ensure_blocks(int(slot),
                                               int(self._t[slot]) + k)):
                if not self._preempt_one():
                    raise RuntimeError(
                        "block pool exhausted with nothing to preempt")
        return bool(self._active.any())

    def _decode_extra_operands(self):
        return (jnp.asarray(self._table),)

    # ------------------------------------------------------------- warmup --

    def _warmup_tasks(self):
        """Paged grid: the shared prefill/seg enumeration (base class —
        this engine overrides only the dispatch helpers) plus ONE decode
        program per table-width bucket — pow2_grid(MB) is the exact set
        _view_cols can select, so warmup covers every decode width
        serving can dispatch.  Prefix-hit admission families ((bucket,
        depth) cached-prefill programs) are compiled on demand: their
        grid is data-dependent (sum over buckets of P/bs programs) and a
        miss there costs one suffix program, not a storm."""
        from .jit.aot import WarmupTask
        tasks = self._prefill_seg_tasks()
        for C in pow2_grid(self.MB):
            tasks.append(WarmupTask(f"decode:{C}",
                                    partial(self._warmup_decode_cols, C)))
        if self.prefix_caching:
            # kvio rides EVERY prefix-caching grid, not just stores:
            # a store-less prefill-role replica still gathers pages at
            # export time and must not pay that compile in serve
            tasks.append(WarmupTask("kvio", self._warmup_kvio))
        return tasks

    def _warmup_prefill(self, P: int):
        run = self._prefill_prog(P)
        ck, cv = self._alloc_caches()
        jax.block_until_ready(run(
            self.params, ck, cv, jnp.zeros((1, P), jnp.int32),
            jnp.int32(0), jnp.zeros(P // self.bs, jnp.int32),
            self._warmup_key(), self._scratch_presence(), jnp.int32(0),
            self._plane_operands()))

    def _warmup_seg(self, first: bool, last: bool):
        seg = self.prefill_chunk
        run = self._seg_prog(seg, first, last)
        ck, cv = self._alloc_caches()
        jax.block_until_ready(run(
            self.params, ck, cv, jnp.zeros((1, seg), jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(0),
            self._scratch_presence(), self._warmup_key(),
            jnp.zeros(self.MB, jnp.int32), self._plane_operands()))

    def _warmup_decode_cols(self, C: int):
        run = self._cached_prog(("decode", C, self._sig),
                                lambda: self._build_decode_cols(C))
        ck, cv = self._alloc_caches()
        z = jnp.zeros(self.S, jnp.int32)
        jax.block_until_ready(run(
            self.params, ck, cv, jnp.zeros((self.S, self.MB), jnp.int32),
            z, z, z, jnp.zeros(self.S, bool), self._warmup_key(),
            self._scratch_presence(), z, self._plane_operands()))

    METRICS_SCHEMA = {
        "blocks_in_use": ("gauge", float),
        "blocks_high_water": ("gauge", float),
        "blocks_allocated": ("counter", float),
        "blocks_released": ("counter", float),
        "preemptions": ("counter", float),
        # present only with enable_prefix_cache=True:
        "blocks_cached": ("gauge", float),
        "prefix_hits": ("counter", float),
        "prefix_blocks_reused": ("counter", float),
        # present only with an attached kv_store (tiered page store):
        "kvstore_restored_blocks": ("counter", float),
        "kvstore_demoted_blocks": ("counter", float),
    }

    def metrics(self):
        m = super().metrics()
        m["blocks_in_use"] = float(self.blocks_in_use)
        m["blocks_high_water"] = float(self.blocks_high_water)
        m["blocks_allocated"] = float(self._stats.value("blocks_allocated"))
        m["blocks_released"] = float(self._stats.value("blocks_released"))
        m["preemptions"] = float(self.preemptions)
        if self.prefix_caching:
            m["blocks_cached"] = float(self._evictable_count())
            m["prefix_hits"] = float(self.prefix_hits)
            m["prefix_blocks_reused"] = float(self.prefix_blocks_reused)
        if self.kv_store is not None:
            m["kvstore_restored_blocks"] = float(
                self._stats.value("kvstore_restored_blocks"))
            m["kvstore_demoted_blocks"] = float(
                self._stats.value("kvstore_demoted_blocks"))
        return m


class RaggedPagedContinuousBatchingEngine(PagedContinuousBatchingEngine):
    """Continuous batching where the WHOLE scheduler tick is ONE compiled
    mixed-batch program (the "ragged paged attention" serving step,
    arxiv 2604.15464 / PAPERS.md).

    The parent engine compiles a prefill program per (bucket, prefix
    depth) plus a separate decode family — prefill and decode tokens can
    never share a step, and every new bucket pays a fresh compile (the
    compile dial that has repeatedly eaten bench rounds; HEALTH.log).
    This engine instead packs every step into ONE flattened ragged token
    batch of at most ``token_budget`` rows:

    - every ACTIVE decode slot contributes its 1 next-token row;
    - the remaining budget is filled with admission-prefill chunks
      (oldest request first) at whatever granularity fits — chunking is
      inherent, so there is no ``prefill_chunk`` knob and no per-bucket
      program family;
    - the model runs the pack through ``decode_ragged`` (k/v scattered
      straight into pool blocks, attention via the ragged Pallas kernel
      or its gather fallback), then ONE (S,)-row sampler draws the next
      token for each decode slot and each prompt that completed this
      step.

    Compiled-program count: one program per (token_budget, table-width
    bucket) — at most log2(max_len/block_size) + 1 programs TOTAL,
    regardless of prompt buckets, prefix depths, or arrival patterns.
    Because only packed rows are computed, there are no parked clocks and
    no inactive-row trash gating: every row in the program is a real
    token.

    The allocator (lazy growth, prefix cache, deferral, youngest-first
    preemption) is inherited unchanged from the paged engine; prompts
    longer than the budget simply span several steps, stalling — not
    failing — when the pool runs dry.  ``ticks_per_sync`` is fixed at 1:
    the budget knob amortizes dispatch instead (one step can carry a
    whole prompt plus every decoder).  Outputs stay oracle-exact vs solo
    ``generate()`` (greedy / deterministic configs), fp32 and int8 pools
    alike.
    """

    def __init__(self, model, params, max_slots: int, max_len: int,
                 token_budget: Optional[int] = None, draft_model=None,
                 draft_params=None, draft_k: int = 4, **kw):
        if kw.get("prefill_chunk") is not None:
            raise ValueError(
                "the ragged engine chunks prefill via token_budget; "
                "prefill_chunk is the bucketed engines' knob")
        if int(kw.pop("ticks_per_sync", 1)) != 1:
            raise NotImplementedError(
                "ragged engine v1 syncs every step — amortize dispatch "
                "with token_budget, not ticks_per_sync")
        if not hasattr(model, "decode_ragged"):
            raise NotImplementedError(
                f"{type(model).__name__} has no decode_ragged path; the "
                f"ragged engine needs the model-side ragged chunk support "
                f"(models/gpt.py) — use PagedContinuousBatchingEngine")
        # ---- speculative decoding INSIDE the ragged tick (ISSUE 13) ----
        # a draft model folds draft proposal + target verification into
        # the SAME one-program-per-(token_budget, table-width) pack; set
        # before super().__init__ — _sig and the program cache key on it
        self.draft_model = draft_model
        self.draft_params = draft_params
        self.K = int(draft_k)
        if draft_model is not None:
            dc = draft_model.config
            if dc.vocab_size != model.config.vocab_size:
                raise ValueError(
                    f"draft vocab ({dc.vocab_size}) != target vocab "
                    f"({model.config.vocab_size})")
            if max_len > dc.max_position_embeddings:
                raise ValueError(
                    f"max_len {max_len} exceeds the DRAFT's "
                    f"max_position_embeddings "
                    f"({dc.max_position_embeddings})")
            if self.K < 1:
                raise ValueError("draft_k must be >= 1")
            if not hasattr(draft_model, "decode_ragged"):
                raise NotImplementedError(
                    f"{type(draft_model).__name__} has no decode_ragged "
                    f"path — the ragged spec step ingests the pack into "
                    f"the draft pool through it")
            # the greedy speculative contract (models/_decode.py): the
            # acceptance rule compares ARGMAX predictions, so sampling
            # and the logits processors are out of scope — exactly the
            # legacy spec engines' v1 scope, now enforced here
            if kw.get("per_request_sampling"):
                raise NotImplementedError(
                    "ragged speculation is greedy-only; "
                    "per_request_sampling is the plain engines' knob")
            if not kw.get("greedy", True):
                raise NotImplementedError(
                    "ragged speculation is greedy-only (the acceptance "
                    "rule is the longest argmax-matching prefix)")
            if float(kw.get("repetition_penalty", 1.0)) != 1.0 \
                    or int(kw.get("min_new_tokens", 0) or 0) != 0:
                raise NotImplementedError(
                    "ragged speculation does not support "
                    "repetition_penalty/min_new_tokens yet")
        super().__init__(model, params, max_slots, max_len, **kw)
        rows_per_slot = (self.K + 1) if draft_model is not None else 1
        tb = (int(token_budget) if token_budget is not None
              else int(max_slots) * rows_per_slot + max(self.buckets))
        if tb < int(max_slots):
            raise ValueError(
                f"token_budget ({tb}) must cover every decode slot "
                f"(max_slots={max_slots})")
        self.token_budget = tb
        # per-slot speculation flag (set at admission from the request's
        # effective spec budget) + the add_request validation seam
        self._spec_slot = np.zeros(self.S, bool)
        self._pending_spec: Optional[bool] = None
        if draft_model is not None:
            # the draft keeps its own block POOL but shares the target's
            # tables and allocator: one allocation covers both models'
            # k/v for a position (the paged-spec composition's design,
            # now on the unified engine)
            self.draft_caches = self._build_pool(dc)

    @property
    def ragged_steps(self) -> int:
        return int(self._stats.value("ragged_steps"))

    @property
    def mixed_steps(self) -> int:
        """Steps that carried prefill AND decode rows."""
        return int(self._stats.value("mixed_steps"))

    @property
    def spec_rounds(self) -> int:
        """Steps that carried at least one slot's draft+verify rows."""
        return int(self._stats.value("spec_rounds"))

    # legacy spec engines' efficiency-reporting attribute (the shims'
    # oracle tests and tools/serve_bench.py read it)
    rounds = spec_rounds

    @property
    def tokens_drafted(self) -> int:
        return int(self._stats.value("tokens_drafted"))

    @property
    def tokens_accepted(self) -> int:
        return int(self._stats.value("tokens_accepted"))

    @property
    def acceptance_rate(self) -> float:
        return self.tokens_accepted / max(self.tokens_drafted, 1)

    @property
    def _sig(self):
        base = PagedContinuousBatchingEngine._sig.fget(self)
        if self.draft_model is None:
            return base
        d = self.draft_model.config
        # the draft's architecture signature rides the program-cache key;
        # _cached_prog additionally pins draft IDENTITY (weakref) — the
        # config tuple alone is not a complete architecture signature
        return base + ("rspec", self.K,
                       (type(self.draft_model).__name__, d.num_layers,
                        d.hidden_size, d.vocab_size,
                        getattr(d, "kv_cache_dtype", None)))

    def _cached_prog(self, cache_key, build):
        """Draft-identity-checked program cache (the legacy spec engines'
        pattern): compiled closures capture the draft model object, so an
        engine over the same target but a different draft instance must
        rebuild, never reuse.  Draft-less engines use the base cache."""
        if self.draft_model is None:
            return super()._cached_prog(cache_key, build)
        import weakref
        progs = self.model.__dict__.setdefault("_serving_programs", {})
        entry = progs.get(cache_key)
        if entry is not None:
            ref, cached = entry
            if ref() is self.draft_model:
                return self._note_prog(cache_key, True, cached)
        run = build()
        # bare program in the cache, wrapper only on the local return
        # (same tracer-lifetime reasoning as the base _cached_prog)
        progs[cache_key] = (weakref.ref(self.draft_model), run)
        return self._note_prog(cache_key, False, run)

    def _positions_needed(self, P: int, mnt: int) -> int:
        spec = (self._pending_spec if self._pending_spec is not None
                else self.draft_model is not None)
        if self.draft_model is not None and spec:
            # budget 1 completes at admission prefill — no round, no
            # slack; otherwise the LAST round can start at t = P + mnt -
            # 2 and write its full K+1-wide verify chunk
            return P if mnt == 1 else P + mnt + self.K - 1
        return super()._positions_needed(P, mnt)

    def add_request(self, prompt, max_new_tokens: int, on_token=None,
                    trace_ctx=None, spec: Optional[bool] = None,
                    **sampling) -> int:
        """The base contract plus the per-request speculative budget:
        ``spec=None`` (default) speculates iff the engine has a draft
        model; ``spec=False`` opts this request out (plain greedy decode
        rows — it shares every tick with speculating neighbours);
        ``spec=True`` requires a draft.  The flag only changes HOW FAST
        the request decodes, never its tokens (greedy contract)."""
        if spec and self.draft_model is None:
            raise ValueError(
                "add_request(spec=True) needs an engine constructed "
                "with draft_model=/draft_params=")
        eff = (self.draft_model is not None) if spec is None else bool(spec)
        self._pending_spec = eff
        try:
            rid = super().add_request(prompt, max_new_tokens,
                                      on_token=on_token,
                                      trace_ctx=trace_ctx, **sampling)
        finally:
            self._pending_spec = None
        self._queue[-1].spec = eff     # base add_request just appended it
        return rid

    def _set_planes(self, slot, req):
        super()._set_planes(slot, req)
        self._spec_slot[slot] = bool(getattr(req, "spec", False))

    # --------------------------------------------------------- scheduling --

    def _admit(self):
        """Admission reserves a slot and (on a prefix hit) pins the cached
        chain — NO device work and NO block allocation happen here; the
        prompt's rows flow into subsequent ragged steps as budget and
        blocks allow."""
        free = self._free_slots()
        while self._queue and free:
            slot = free.pop(0)
            req = self._queue.pop(0)
            P = select_bucket(len(req.prompt), self.buckets)
            pad = P - len(req.prompt)
            ids = [0] * pad + req.prompt
            F, hit = (self._lookup_prefix(ids, pad, P)
                      if self.prefix_caching else (0, []))
            if F:
                for bid in hit:                   # pin before eviction runs
                    self._pin(bid)
                self._table[slot, :F] = hit
                self._nblk[slot] = F
                self._stats.add("prefix_hits")
                self._stats.add("prefix_blocks_reused", F)
            self._seq += 1
            self._admit_seq[slot] = self._seq
            self._set_planes(slot, req)
            self._pad[slot] = pad
            self._t[slot] = 0
            if self._track:
                # presence seeds from the FULL prompt at admission (shared
                # prefix tokens count for the penalty even though their
                # rows are never recomputed) — a host-built row, not a
                # compiled program family
                V = self.model.config.vocab_size
                row = np.zeros((1, V), bool)
                # clip == the device scatter's out-of-vocab clamping
                # (seed_presence); numpy fancy indexing would raise and
                # leave the slot half-admitted
                row[0, np.clip(np.asarray(req.prompt, np.int64),
                               0, V - 1)] = True
                self._presence = jax.lax.dynamic_update_slice(
                    self._presence, jnp.asarray(row), (slot, 0))
            self._filling[slot] = {"req": req, "ids": ids, "pad": pad,
                                   "P": P, "filled": F * self.bs}

    def _build_pack(self):
        """Assemble one step's flattened ragged pack: all active decode
        rows first (block coverage grown via _prepare_decode, preempting
        the youngest when dry), then prefill chunks oldest-first into the
        remaining budget (a dry pool shrinks the chunk — the filler
        stalls while decode retirements free blocks).  Returns None when
        there is nothing to run.

        With a draft model, a speculating slot claims K extra rows right
        after its next-token row — the verify chunk [prev, d_0..d_{K-1}]
        at kv positions [t, t+K].  The draft TOKEN VALUES are filled
        in-program (the host cannot know them); only row metadata is
        packed here.  Speculation is per-slot OPPORTUNISTIC: a tight
        budget, a dry pool, or missing cache room degrades the slot to a
        plain decode row for this step — never stalls it."""
        T = self.token_budget
        if self._active.any():
            self._prepare_decode()        # table growth + preemption loop
        toks = np.zeros(T, np.int32)
        row_seq = np.full(T, -1, np.int32)
        row_pos = np.full(T, -1, np.int32)
        sample_rows = np.zeros(self.S, np.int32)
        sample_active = np.zeros(self.S, bool)
        spec_row0 = np.zeros(self.S, np.int32)
        spec_active = np.zeros(self.S, bool)
        K = self.K if self.draft_model is not None else 0
        n = 0
        dec_slots = []
        act = [int(s) for s in np.flatnonzero(self._active)]
        for idx, slot in enumerate(act):
            toks[n] = self._tok[slot]
            row_seq[n] = slot
            row_pos[n] = self._t[slot]
            sample_rows[slot] = n
            sample_active[slot] = True
            dec_slots.append(slot)
            n += 1
            remaining = len(act) - idx - 1    # slots still owed 1 row
            t = int(self._t[slot])
            if (K and self._spec_slot[slot]
                    and t + K + 1 <= self.max_len
                    and n + K + remaining <= T
                    and self._ensure_blocks(slot, t + K + 1)):
                for j in range(K):
                    row_seq[n] = slot
                    row_pos[n] = t + 1 + j
                    n += 1
                spec_row0[slot] = n - K
                spec_active[slot] = True
        fill_adv = {}
        for slot in sorted(self._filling,
                           key=lambda s: int(self._admit_seq[s])):
            if n >= T:
                break
            st = self._filling[slot]
            want = min(st["P"] - st["filled"], T - n)
            have = int(self._nblk[slot])
            if have * self.bs < st["filled"] + want:
                # grant what the pool can actually cover in ONE
                # transactional request (one prefix-cache scan, not one
                # per block) — a dry pool shrinks the chunk and the
                # filler stalls while decode retirements free blocks
                grantable = len(self._free) + self._evictable_count()
                need = -(-(st["filled"] + want) // self.bs) - have
                take = min(need, grantable)
                if take > 0:
                    self._ensure_blocks(slot, (have + take) * self.bs)
            m = min(want, int(self._nblk[slot]) * self.bs - st["filled"])
            if m <= 0:
                continue
            for k in range(m):
                toks[n] = st["ids"][st["filled"] + k]
                row_seq[n] = slot
                row_pos[n] = st["filled"] + k
                n += 1
            fill_adv[slot] = m
            if st["filled"] + m == st["P"]:
                # the prompt's last row yields the first-token hidden state
                sample_rows[slot] = n - 1
                sample_active[slot] = True
        if n == 0:
            # jointly wedged fillers with no decoder: nothing will ever
            # free blocks — evict the youngest so the oldest progresses
            # (the chunked-prefill discipline); rows are empty, so no
            # packed state is invalidated by the eviction
            if self._filling and self._preempt_one():
                return self._build_pack()
            return None
        need_cols = -(-(int(row_pos[:n].max()) + 1) // self.bs)
        C = pow2_bucket(need_cols, self.MB)
        if dec_slots and fill_adv:
            self._stats.add("mixed_steps")
        return (toks, row_seq, row_pos, C, sample_rows, sample_active,
                dec_slots, fill_adv, spec_row0, spec_active)

    def _step_impl(self):
        """One scheduler round = ONE device program: admit, pack, run the
        ragged step, unpack sampled tokens (decode slots advance;
        completed prompts activate with their first token).  With a
        draft model the same round runs the fused draft+verify program
        instead — still one compiled program per (token_budget,
        table-width) bucket."""
        self._admit()
        pack = self._build_pack()
        if pack is None:
            return
        (toks, row_seq, row_pos, C, sample_rows, sample_active, dec_slots,
         fill_adv, spec_row0, spec_active) = pack
        if self.draft_model is not None:
            return self._run_spec_pack(toks, row_seq, row_pos, C,
                                       sample_rows, dec_slots, fill_adv,
                                       spec_row0, spec_active)
        if self.tracer is not None:
            pf = int(sum(fill_adv.values()))
            note = self._tick_note
            note["decode_rows"] = note.get("decode_rows", 0) \
                + len(dec_slots)
            note["prefill_tokens"] = note.get("prefill_tokens", 0) + pf
            note["budget_used"] = note.get("budget_used", 0) \
                + len(dec_slots) + pf
            note["token_budget"] = self.token_budget
            note["table_cols"] = C
        emitted0 = np.asarray(
            [len(self._slot_req[s].generated) if self._active[s] else 0
             for s in range(self.S)], np.int32)
        run = self._ragged_prog(C)
        ck, cv, ntok, self._presence = run(
            self.params, self.caches[0], self.caches[1],
            jnp.asarray(toks), jnp.asarray(row_seq), jnp.asarray(row_pos),
            jnp.asarray(self._table[:, :C]), jnp.asarray(self._pad),
            jnp.asarray(sample_rows), jnp.asarray(sample_active),
            jnp.asarray(emitted0), self._next_key(), self._presence,
            self._plane_operands())
        self.caches = (ck, cv)
        self._stats.add("ragged_steps")
        ntok = np.asarray(ntok)
        for slot in dec_slots:
            self._t[slot] += 1
            self._tok[slot] = int(ntok[slot])
            self._record(slot, int(ntok[slot]))
            # room safety net (admission-validated budgets never trigger)
            if self._active[slot] and int(self._t[slot]) + 1 > self.max_len:
                self._retire(slot)
        for slot, m in fill_adv.items():
            st = self._filling[slot]
            st["filled"] += m
            if st["filled"] == st["P"]:
                del self._filling[slot]
                self._register_prompt_blocks(slot, st["ids"], st["pad"],
                                             st["P"])
                self._activate(slot, st["req"], st["P"], st["pad"],
                               int(ntok[slot]))

    # ---------------------------------------------------------- programs --

    def _ragged_prog(self, C: int):
        """ONE program per (token_budget, table-width bucket) — the whole
        mixed admission+decode tick, no per-bucket prefill family."""
        return self._cached_prog(
            ("ragged_step", self.token_budget, C, self._sig),
            lambda: self._build_ragged_step(self.token_budget, C))

    def _build_ragged_step(self, T: int, C: int):
        model = self.model
        track = self._track
        S = self.S
        sample = self._sample
        rp, min_new, eos = self._sample_sig[4:]
        per_request = self.per_request
        row_sample = self._row_sample if per_request else None

        @partial(jax.jit, donate_argnums=(1, 2, 12))
        def run(params, pool_ck, pool_cv, toks, row_seq, row_pos, table,
                pads, sample_rows, sample_active, emitted0, key, presence,
                planes):
            h = model._embed_ragged(params, toks, row_seq, row_pos, pads)
            h, (pool_ck, pool_cv) = model.decode_ragged(
                params, h, (pool_ck, pool_cv), table, row_seq, row_pos,
                pads)
            # ONE sampler over S gathered rows: each decode slot's row and
            # each completing prompt's last row (dummy row 0 for the rest
            # — computed, ignored host-side)
            h_s = h[0, sample_rows][:, None]            # (S, 1, H)
            l2 = model.decode_logits(params, h_s)[:, -1]
            key, sub = jax.random.split(key)
            if per_request:
                temp, topk, topp, greedy, rpv, mnv, eosv = planes
                l2 = apply_repetition_penalty(l2, presence, rpv)
                l2 = suppress_eos_rows(l2, eosv, emitted0 < mnv)
                ntok = row_sample(l2[:, None, :], sub, temp, topk, topp,
                                  greedy)
            else:
                if track:
                    l2 = apply_repetition_penalty(l2, presence, rp)
                if min_new > 0:
                    l2 = suppress_eos(l2, eos, emitted0 < min_new)
                ntok = sample(l2[:, None, :], sub)
            if track:
                # prompt tokens were seeded at admission; only SAMPLED
                # tokens update presence in-program
                presence = presence.at[jnp.arange(S), ntok].max(
                    sample_active)
            return pool_ck, pool_cv, ntok, presence

        return run

    # ------------------------------------------- speculative ragged step --

    def _run_spec_pack(self, toks, row_seq, row_pos, C, sample_rows,
                       dec_slots, fill_adv, spec_row0, spec_active):
        """Dispatch one fused draft+verify ragged step and unpack: each
        speculating slot advances by its accepted count + 1 (greedy
        contract — outputs equal plain decode by construction), plain
        decode slots and completing prompts advance by their single
        sampled token through the SAME program."""
        K = self.K
        n_spec = int(spec_active.sum())
        if self.tracer is not None:
            pf = int(sum(fill_adv.values()))
            note = self._tick_note
            note["decode_rows"] = note.get("decode_rows", 0) \
                + len(dec_slots)
            note["spec_rows"] = note.get("spec_rows", 0) + n_spec * K
            note["prefill_tokens"] = note.get("prefill_tokens", 0) + pf
            note["budget_used"] = note.get("budget_used", 0) \
                + len(dec_slots) + n_spec * K + pf
            note["token_budget"] = self.token_budget
            note["table_cols"] = C
        run = self._ragged_spec_prog(C)
        ck, cv, dck, dcv, lead, block = run(
            (self.params, self.draft_params), self.caches[0],
            self.caches[1], self.draft_caches[0], self.draft_caches[1],
            jnp.asarray(toks), jnp.asarray(row_seq), jnp.asarray(row_pos),
            jnp.asarray(self._table[:, :C]), jnp.asarray(self._pad),
            jnp.asarray(sample_rows), jnp.asarray(spec_row0),
            jnp.asarray(spec_active), jnp.asarray(self._tok),
            jnp.asarray(self._t))
        self.caches = (ck, cv)
        self.draft_caches = (dck, dcv)
        self._stats.add("ragged_steps")
        if n_spec:
            self._stats.add("spec_rounds")
            self._stats.add("tokens_drafted", n_spec * K)
        lead = np.asarray(lead)
        block = np.asarray(block)
        for slot in dec_slots:
            m = int(lead[slot]) + 1 if spec_active[slot] else 1
            if spec_active[slot]:
                self._stats.add("tokens_accepted", int(lead[slot]))
            for j in range(m):
                if not self._active[slot]:
                    break              # retired/cancelled mid-round:
                self._t[slot] += 1     # discard the round's tail
                self._tok[slot] = int(block[slot, j])
                self._record(slot, int(block[slot, j]))
            if self._active[slot]:
                if int(self._t[slot]) + 1 > self.max_len:
                    self._retire(slot)         # room safety net
                elif spec_active[slot]:
                    # KV rollback: whole blocks past the accepted clock
                    # held only REJECTED draft pages — return them to
                    # the pool now instead of stranding them until
                    # retirement (self-healing writes make the next
                    # round's fresh blocks safe by construction)
                    self._rollback_blocks(slot)
        for slot, m in fill_adv.items():
            st = self._filling[slot]
            st["filled"] += m
            if st["filled"] == st["P"]:
                del self._filling[slot]
                self._register_prompt_blocks(slot, st["ids"], st["pad"],
                                             st["P"])
                # a completing prompt's first token rides block[:, 0]
                # (its lead is 0 through the shared acceptance gather)
                self._activate(slot, st["req"], st["P"], st["pad"],
                               int(block[slot, 0]))

    def _rollback_blocks(self, slot: int):
        """Free the slot's table columns past the accepted clock — the
        pages that only ever held rejected draft k/v.  Columns holding
        any accepted position are kept; prompt/prefix blocks sit below
        the decode clock and are never touched."""
        keep = -(-int(self._t[slot]) // self.bs)
        have = int(self._nblk[slot])
        if have <= keep:
            return
        for c in range(have - 1, keep - 1, -1):
            self._release(int(self._table[slot, c]))
            self._table[slot, c] = 0
        self._nblk[slot] = keep

    def _ragged_spec_prog(self, C: int):
        """ONE fused draft+verify program per (token_budget, table-width
        bucket) — speculation adds ZERO program families on top of the
        ragged grid (the draft's prompt ingestion rides the same pack)."""
        return self._cached_prog(
            ("ragged_spec", self.token_budget, C, self._sig),
            lambda: self._build_ragged_spec_step(self.token_budget, C))

    def _build_ragged_spec_step(self, T: int, C: int):
        """The whole speculative tick as ONE compiled program: (1) the
        draft proposes K greedy tokens per speculating slot over its
        paged pool (table gated to speculating rows — everyone else's
        writes land in trash); (2) the proposals are scattered into the
        flattened pack at their host-assigned rows; (3) the target runs
        the WHOLE mixed pack (prefill chunks + plain decode rows +
        verify chunks) through decode_ragged; (4) the draft ingests the
        SAME pack — prompt rows keep its pool current (so a draft-less
        admission never exists, and non-spec steps still feed it), and
        the verify rows write d_{K-1}'s k/v (the legacy self-heal, for
        free); (5) greedy verification gathers each slot's K+1 rows and
        applies the shared models/_decode.greedy_verify contract."""
        model, draft = self.model, self.draft_model
        K, S = self.K, self.S
        Ld = draft.config.num_layers

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def run(params_pair, pool_ck, pool_cv, dpool_ck, dpool_cv, toks,
                row_seq, row_pos, table, pads, sample_rows, spec_row0,
                spec_active, dec_tok, dec_t):
            params, dparams = params_pair
            # (1) draft proposal scan (S-wide; non-spec rows compute
            # garbage into the trash block via the gated table)
            tb = jnp.where(spec_active[:, None], table, 0)
            tbD = jnp.broadcast_to(tb[None], (Ld,) + tb.shape)
            dkv = (PagedKV(dpool_ck, tbD), PagedKV(dpool_cv, tbD))

            def dstep(carry, i):
                tok, dc = carry
                hh = draft._embed_one(dparams, tok, dec_t + i,
                                      pad_lens=pads)
                hh, dc = draft.decode_step(dparams, hh, dc, dec_t + i,
                                           pad_lens=pads)
                ntok = jnp.argmax(
                    draft.decode_logits(dparams, hh)[:, -1],
                    -1).astype(jnp.int32)
                return (ntok, dc), ntok

            (_, dkv), d = jax.lax.scan(dstep, (dec_tok, dkv),
                                       jnp.arange(K))
            d = d.T                                        # (S, K)
            dpool_ck, dpool_cv = dkv[0].pool, dkv[1].pool
            # (2) scatter proposals into the pack; non-spec rows target
            # index T (out of bounds) and DROP
            drows = jnp.where(spec_active[:, None],
                              spec_row0[:, None] + jnp.arange(K)[None],
                              T)
            toks = toks.at[drows].set(d, mode="drop")
            # (3) one target pass over the whole mixed pack
            h = model._embed_ragged(params, toks, row_seq, row_pos, pads)
            h, (pool_ck, pool_cv) = model.decode_ragged(
                params, h, (pool_ck, pool_cv), table, row_seq, row_pos,
                pads)
            # (4) the draft ingests the same pack (prompt currency +
            # d_{K-1} self-heal)
            hd = draft._embed_ragged(dparams, toks, row_seq, row_pos,
                                     pads)
            _, (dpool_ck, dpool_cv) = draft.decode_ragged(
                dparams, hd, (dpool_ck, dpool_cv), table, row_seq,
                row_pos, pads)
            # (5) greedy verification: gather each slot's K+1 rows (non-
            # spec slots gather their single row K+1 times — their lead
            # is forced to 0, so block[:, 0] is plain greedy decode)
            grows = sample_rows[:, None] + jnp.arange(K + 1)[None] \
                * spec_active[:, None].astype(jnp.int32)
            h_s = h[0, grows]                              # (S, K+1, H)
            tpred = jnp.argmax(model.decode_logits(params, h_s),
                               -1).astype(jnp.int32)       # (S, K+1)
            lead, block = greedy_verify(d, tpred, active=spec_active)
            return pool_ck, pool_cv, dpool_ck, dpool_cv, lead, block

        return run

    # ------------------------------------------------------------- warmup --

    def _warmup_tasks(self):
        """The ragged engine's whole compile grid is ONE program per
        (token_budget, table-width bucket) — pow2_grid(MB) enumerates it
        completely, so a warmed engine never compiles on the serving
        path (compile count 0 for ANY arrival pattern).  With a draft
        model the grid is the same SIZE: the fused draft+verify program
        replaces the plain one bucket for bucket (speculation adds zero
        program families — the draft prefills through the same pack)."""
        from .jit.aot import WarmupTask
        if self.draft_model is not None:
            tasks = [WarmupTask(f"ragged_spec:{self.token_budget}:{C}",
                                partial(self._warmup_ragged_spec, C))
                     for C in pow2_grid(self.MB)]
        else:
            tasks = [WarmupTask(f"ragged_step:{self.token_budget}:{C}",
                                partial(self._warmup_ragged, C))
                     for C in pow2_grid(self.MB)]
        if self.prefix_caching:
            # same reasoning as the paged grid: export-side gathers on
            # store-less prefill-role replicas are part of the grid too
            tasks.append(WarmupTask("kvio", self._warmup_kvio))
        return tasks

    def _ragged_scratch_args(self, C: int):
        """Scratch operand tuple for one table-width bucket's ragged
        program: fresh pools (donated and freed), rows all parked on slot
        0 / the trash table — values are irrelevant, shapes and dtypes
        ARE the program signature (the purity test lowers through these)."""
        ck, cv = self._alloc_caches()
        T, S = self.token_budget, self.S
        z = jnp.zeros(S, jnp.int32)
        return (self.params, ck, cv, jnp.zeros(T, jnp.int32),
                jnp.zeros(T, jnp.int32),
                jnp.minimum(jnp.arange(T, dtype=jnp.int32),
                            C * self.bs - 1),
                jnp.zeros((S, C), jnp.int32), z, z,
                jnp.zeros(S, bool), z, self._warmup_key(),
                self._scratch_presence(), self._plane_operands())

    def _warmup_ragged(self, C: int):
        run = self._ragged_prog(C)
        jax.block_until_ready(run(*self._ragged_scratch_args(C)))

    def _ragged_spec_scratch_args(self, C: int):
        """Scratch operands for one fused draft+verify program (fresh
        donated pools for BOTH models; rows parked on slot 0 / trash —
        shapes and dtypes ARE the signature, values are irrelevant)."""
        ck, cv = self._alloc_caches()
        dck, dcv = self._build_pool(self.draft_model.config)
        T, S = self.token_budget, self.S
        z = jnp.zeros(S, jnp.int32)
        return ((self.params, self.draft_params), ck, cv, dck, dcv,
                jnp.zeros(T, jnp.int32), jnp.zeros(T, jnp.int32),
                jnp.minimum(jnp.arange(T, dtype=jnp.int32),
                            C * self.bs - 1),
                jnp.zeros((S, C), jnp.int32), z, z, z,
                jnp.zeros(S, bool), z, z)

    def _warmup_ragged_spec(self, C: int):
        run = self._ragged_spec_prog(C)
        jax.block_until_ready(run(*self._ragged_spec_scratch_args(C)))

    _TICK_COUNTERS = (PagedContinuousBatchingEngine._TICK_COUNTERS
                      + ("tokens_drafted", "tokens_accepted"))

    METRICS_SCHEMA = {
        "ragged_steps": ("counter", float),
        "mixed_steps": ("counter", float),
        # present only with a draft model (ragged speculation):
        "spec_rounds": ("counter", int),
        "tokens_drafted": ("counter", int),
        "tokens_accepted": ("counter", int),
        "acceptance_rate": ("gauge", float),
        "accepted_tokens_per_s": ("gauge", float),
    }

    def metrics(self):
        m = super().metrics()
        m["ragged_steps"] = float(self.ragged_steps)
        m["mixed_steps"] = float(self.mixed_steps)
        if self.draft_model is not None:
            dt = max(time.monotonic() - self._started, 1e-9)
            m["spec_rounds"] = self.spec_rounds
            m["tokens_drafted"] = self.tokens_drafted
            m["tokens_accepted"] = self.tokens_accepted
            m["acceptance_rate"] = float(self.acceptance_rate)
            m["accepted_tokens_per_s"] = self.tokens_accepted / dt
        return m


# ---------------------------------------------------------------------------
# legacy speculative engines — deprecation shims over the ragged spec path
# ---------------------------------------------------------------------------

_SPEC_SHIM_WARNED: set = set()


def _warn_spec_shim(name: str):
    """Warn ONCE per legacy engine class (the deprecation contract)."""
    if name in _SPEC_SHIM_WARNED:
        return
    _SPEC_SHIM_WARNED.add(name)
    import warnings
    warnings.warn(
        f"{name} is deprecated: speculative decoding now runs INSIDE "
        f"RaggedPagedContinuousBatchingEngine (draft_model=/draft_k= "
        f"constructor args) as part of the one-program-per-tick ragged "
        f"pack; this shim maps the legacy constructor onto the unified "
        f"engine", DeprecationWarning, stacklevel=3)


class SpeculativeBatchingEngine(RaggedPagedContinuousBatchingEngine):
    """DEPRECATED shim: the pre-ragged speculative engine (its own
    spec_prefill-per-bucket + spec_round program family) is gone —
    speculation now runs inside the ragged engine's single fused
    draft+verify program per (token_budget, table-width) bucket.  This
    shim maps the legacy contiguous constructor (no storage knobs) onto
    the unified engine, deriving a block size from max_len and the
    bucket ladder.  Outputs keep the greedy contract: token for token
    equal to plain decode, with rounds shrinking by the acceptance rate
    (``engine.rounds`` still reports them)."""

    _SUPPORTED_CACHE_KW = frozenset({"tracer"})

    def __init__(self, model, params, draft_model, draft_params,
                 max_slots: int, max_len: int, draft_k: int = 4,
                 prompt_buckets=None, eos_token_id=None, key=None,
                 mesh=None, **cache_kw):
        _warn_spec_shim(type(self).__name__)
        if mesh is not None:
            raise NotImplementedError(
                "speculative engine v1 is single-mesh")
        # the legacy scope guard: sampler knobs the greedy round would
        # silently ignore (and storage knobs this shim has no notion of)
        # are rejected loudly, exactly as before
        bad = set(cache_kw) - self._SUPPORTED_CACHE_KW
        if bad:
            raise NotImplementedError(
                f"{type(self).__name__} does not support {sorted(bad)}")
        buckets = (_default_buckets(max_len) if prompt_buckets is None
                   else sorted(set(int(b) for b in prompt_buckets)))
        # the contiguous engine had no block size; pick the largest one
        # that divides max_len and every bucket (>= 1 always works)
        bs = math.gcd(int(max_len), *[int(b) for b in buckets])
        super().__init__(model, params, max_slots, max_len,
                         draft_model=draft_model,
                         draft_params=draft_params, draft_k=draft_k,
                         prompt_buckets=buckets,
                         eos_token_id=eos_token_id, key=key,
                         block_size=bs, **cache_kw)


class PagedSpeculativeBatchingEngine(SpeculativeBatchingEngine):
    """DEPRECATED shim: the paged-speculative composition (dual-pool
    prefill/seg programs + spec_round_paged per table width) is gone —
    the unified ragged engine already keeps the draft pool behind the
    target's tables and allocator, so this shim only forwards the
    storage knobs.  ``prefill_chunk`` is accepted and dropped: the
    ragged engine chunks prefill inherently via token_budget."""

    _SUPPORTED_CACHE_KW = frozenset({"block_size", "num_blocks",
                                     "enable_prefix_cache",
                                     "prefill_chunk", "tracer"})

    def __init__(self, model, params, draft_model, draft_params,
                 max_slots: int, max_len: int, draft_k: int = 4,
                 prompt_buckets=None, eos_token_id=None, key=None,
                 block_size: int = 16, num_blocks=None, **kw):
        _warn_spec_shim(type(self).__name__)
        bad = set(kw) - self._SUPPORTED_CACHE_KW
        if bad:
            raise NotImplementedError(
                f"{type(self).__name__} does not support {sorted(bad)}")
        kw.pop("prefill_chunk", None)   # ragged chunks via token_budget
        RaggedPagedContinuousBatchingEngine.__init__(
            self, model, params, max_slots, max_len,
            draft_model=draft_model, draft_params=draft_params,
            draft_k=draft_k, prompt_buckets=prompt_buckets,
            eos_token_id=eos_token_id, key=key, block_size=block_size,
            num_blocks=num_blocks, **kw)
