"""Live ops endpoint: a stdlib HTTP server over the telemetry surfaces.

Everything PR 2/4/8 record — tracer ring buffers, TrainMonitor counters,
engine stat registries, the goodput ledger — lives in process memory and is
only visible post-hoc through JSONL dumps.  :class:`OpsServer` makes it
live: a ``ThreadingHTTPServer`` (stdlib only, no new deps) that any engine,
``TrainMonitor``, ``Tracer`` or ``RunLedger`` can be attached to, serving

``GET /metrics``
    merged Prometheus text exposition of every attached source — serving
    (``paddle_tpu_serving_*``) and training (``paddle_tpu_train_*``)
    namespaces side by side, engine registries, ledger gauges
    (``paddle_tpu_ledger_*``), plus the server's own uptime gauge.
``GET /healthz``
    liveness JSON; **503** when the last observed step/tick/heartbeat is
    older than ``stall_threshold_s`` — the load-balancer / watchdog dial.
    ``?probe=1`` additionally runs an in-process compute probe with the
    same semantics as ``bench.py``'s backend probe (a jitted matmul
    ROUND-TRIP to host, never a bare ``jax.devices()`` — a half-up
    backend enumerates devices while compile/execute hangs), bounded by
    ``probe_timeout_s``.
``GET /ledger``
    the attached :class:`~paddle_tpu.telemetry_ledger.RunLedger`
    snapshot(s) as JSON (404 when none is attached).
``GET /trace``
    ring-buffer tail: the last ``?n=`` events (default 256) per attached
    tracer/monitor, optionally filtered by ``?kind=``.
``GET /gateway``
    the attached :class:`~paddle_tpu.gateway.ServingGateway` snapshot(s)
    as JSON — replica states, per-priority queue depths, shed/reroute/
    drain counters, queue/TTFT percentiles (404 when none is attached).
``GET /requests``
    recent end-to-end request traces (``?n=`` newest, default 64):
    trace_id, status, replicas touched — stitched live from every
    attached tracer's ring by
    :class:`~paddle_tpu.telemetry.RequestTraceIndex`.
``GET /request/<trace_id>``
    ONE stitched request timeline: the full cross-source span tree
    (gateway root → per-dispatch engine attempts → queued/prefill/
    decode phases, preempt markers) plus the raw event sequence (404
    for an unknown trace).
``GET /resilience``
    the attached gateway's resilience view (PR 12): per-replica circuit
    breaker states, the brownout ladder rung, live hedges, and the
    retry/hedge/brownout counters (404 when no attached gateway carries
    a resilience policy).
``GET /slo``
    the attached :class:`~paddle_tpu.telemetry_slo.SLOMonitor` snapshot:
    objectives, live burn rates, alert states, SLIs, and the recent
    transition ring (404 when none is attached); scraping evaluates, so
    the states are current as of the request.
``GET /autoscaler``
    the attached :class:`~paddle_tpu.autoscaler.ElasticAutoscaler`
    snapshot: policy knobs, fleet/pending-spawn state, live signals
    (firing objectives, utilization, idle dwell), and the bounded
    decision history (404 when none is attached).  A pure read — it
    never advances the control loop.
``GET /kvstore``
    the KV-tiering view (docs/KV_TIERING.md): attached gateways'
    ``kvstore_snapshot()`` (migration counters + in-flight pipelines,
    per-replica role/store state, the fleet-wide tier-aware prefix
    index) plus any directly attached
    :class:`~paddle_tpu.kv_store.TieredKVStore` snapshots (404 when
    nothing KV-tiered is attached).
``GET /memory``
    the attached :class:`~paddle_tpu.telemetry_memory.MemoryLedger`
    snapshot(s): per-pool live/peak bytes in device and host space, KV
    tier bytes, per-device totals from the last census, and the
    watermark-crossing tail (404 when none is attached).  A pure read —
    it never runs a census; callers decide when the live-array walk
    happens.
``GET /fleet``
    the attached :class:`~paddle_tpu.telemetry_fleet.FleetCollector`
    snapshot(s): per-target scrape status (``ok``/``stale``/``down``
    with ages and last errors), the fleet rollups (global goodput,
    fleet MFU, merged TTFT/ITL percentiles, straggler skew), fleet SLO
    burn, and spool stats (404 when none is attached).  A pure read of
    the LAST scrape — it never triggers one.

Zero cost when not started: constructing the server binds nothing and
touches no hot path — sources are only read inside request handlers.
``start()`` binds (``port=0`` → ephemeral) and serves on a daemon thread.

Example::

    from paddle_tpu.ops_server import OpsServer
    srv = OpsServer(port=9100, stall_threshold_s=120)
    srv.attach(engine)          # engine registry + its tracer, if any
    srv.attach(monitor)         # TrainMonitor
    srv.attach(ledger)          # RunLedger
    url = srv.start()
    # curl $url/metrics ; curl $url/healthz ; curl $url/ledger
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["OpsServer", "compute_probe"]


def compute_probe(timeout_s: float = 10.0, n: int = 256) -> Dict[str, Any]:
    """In-process compute health probe — the same semantics as
    ``bench.py``'s backend probe: health is a jitted ``n×n`` matmul
    round-trip to host (compile + execute + fetch), never a bare device
    enumeration.  Runs on a worker thread bounded by ``timeout_s``; on
    timeout the thread is abandoned (reported unhealthy), not killed — an
    in-process probe cannot kill its own interpreter."""
    result: Dict[str, Any] = {}

    def run():
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np
            t0 = time.perf_counter()
            x = jnp.ones((n, n), jnp.float32)
            # tpulint: disable=jit-in-hot-loop(one-shot probe — paying trace+compile+execute is the health check itself, bench.py probe parity)
            v = float(np.asarray(jax.jit(lambda a: a @ a)(x)[0, 0]))
            result.update(ok=True, value=v,
                          wall_s=round(time.perf_counter() - t0, 4),
                          devices=len(jax.devices()))
        except Exception as e:       # the probe verdict IS the error report
            result.update(ok=False, error=repr(e))

    t = threading.Thread(target=run, daemon=True, name="ops-compute-probe")
    t.start()
    t.join(timeout_s)
    if not result:
        return {"ok": False,
                "error": f"compute probe timed out after {timeout_s}s "
                         f"(dispatch or compile hung — half-up backend)"}
    return result


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-ops/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self):          # noqa: N802 — http.server contract
        ops: "OpsServer" = self.server.ops     # type: ignore[attr-defined]
        parsed = urllib.parse.urlsplit(self.path)
        route = parsed.path.rstrip("/") or "/"
        query = urllib.parse.parse_qs(parsed.query)
        try:
            if route == "/metrics":
                self._send(200, ops._render_metrics(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                payload, ok = ops._render_healthz(
                    run_probe=query.get("probe", ["0"])[0]
                    not in ("0", "", "false"))
                self._send(200 if ok else 503,
                           json.dumps(payload, indent=2),
                           "application/json")
            elif route == "/ledger":
                payload = ops._render_ledger()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no ledger attached"}), "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/trace":
                n = int(query.get("n", ["256"])[0])
                kind = query.get("kind", [None])[0]
                self._send(200, json.dumps(ops._render_trace(n, kind)),
                           "application/json")
            elif route == "/gateway":
                payload = ops._render_gateway()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no gateway attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/requests":
                n = int(query.get("n", ["64"])[0])
                self._send(200, json.dumps(ops._render_requests(n),
                                           indent=2), "application/json")
            elif route.startswith("/request/"):
                trace_id = route[len("/request/"):]
                payload = ops._render_request(trace_id)
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": f"unknown trace {trace_id!r}"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/resilience":
                payload = ops._render_resilience()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no resilience-enabled gateway "
                                  "attached"}), "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/slo":
                payload = ops._render_slo()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no slo monitor attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/autoscaler":
                payload = ops._render_autoscaler()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no autoscaler attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/kvstore":
                payload = ops._render_kvstore()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "nothing KV-tiered attached (no "
                                  "kv-surface gateway, no TieredKVStore)"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/memory":
                payload = ops._render_memory()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no memory ledger attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/fleet":
                payload = ops._render_fleet()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no fleet collector attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            elif route == "/train":
                payload = ops._render_train()
                if payload is None:
                    self._send(404, json.dumps(
                        {"error": "no train supervisor attached"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": f"unknown route {route!r}", "routes":
                     ["/metrics", "/healthz", "/ledger", "/trace",
                      "/gateway", "/requests", "/request/<trace_id>",
                      "/resilience", "/slo", "/autoscaler", "/kvstore",
                      "/memory", "/fleet", "/train"]}),
                    "application/json")
        except Exception as e:
            ops._log.warning("ops server: %s failed: %r", route, e)
            try:
                self._send(500, json.dumps({"error": repr(e)}),
                           "application/json")
            except OSError:
                pass                      # client went away mid-error

    def _send(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):     # route through logging, not stderr
        self.server.ops._log.debug(        # type: ignore[attr-defined]
            "ops server: %s", fmt % args)


class OpsServer:
    """Attachable live ops endpoint (module docstring).

    ``stall_threshold_s``: /healthz turns 503 when no attached source has
    shown activity (train step, scheduler tick, explicit ``heartbeat()``)
    for longer than this.  ``probe_timeout_s`` bounds the optional
    ``?probe=1`` compute probe."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 stall_threshold_s: float = 120.0,
                 probe_timeout_s: float = 10.0,
                 logger: Optional[logging.Logger] = None):
        self.host = host
        self.port = int(port)
        self.stall_threshold_s = float(stall_threshold_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._tracers: List[Tuple[str, Any]] = []   # Tracer / TrainMonitor
        self._engines: List[Tuple[str, Any]] = []
        self._ledgers: List[Tuple[str, Any]] = []
        self._gateways: List[Tuple[str, Any]] = []
        self._slos: List[Tuple[str, Any]] = []      # SLOMonitor
        self._autoscalers: List[Tuple[str, Any]] = []
        self._kvstores: List[Tuple[str, Any]] = []  # TieredKVStore
        self._memories: List[Tuple[str, Any]] = []  # MemoryLedger
        self._fleets: List[Tuple[str, Any]] = []    # FleetCollector
        self._trains: List[Tuple[str, Any]] = []    # TrainSupervisor
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_at = time.monotonic()
        self._last_beat = time.monotonic()

    # ------------------------------------------------------------ attach --
    def attach(self, obj, name: Optional[str] = None) -> "OpsServer":
        """Attach a telemetry source; kind is detected:

        - ``FleetCollector`` (has ``fleet_snapshot``) → /fleet + its
          ``paddle_tpu_fleet_*`` federation gauges on /metrics;
        - ``RunLedger`` (has ``snapshot``/``record``) → /ledger + gauges;
        - ``MemoryLedger`` (has ``memory_snapshot``) → /memory +
          /metrics pool/watermark byte gauges;
        - ``ElasticAutoscaler`` (has ``autoscaler_snapshot``) →
          /autoscaler + /metrics fleet/decision gauges;
        - ``ServingGateway`` (has ``gateway_snapshot``) → /gateway +
          /metrics (its ``.tracer``, when set, is attached too);
        - ``TieredKVStore`` (has ``tier_of``/``put``) → /kvstore +
          /metrics tier gauges (attached gateways contribute their
          replicas' stores to /kvstore without this);
        - ``SLOMonitor`` (has ``add_objective``/``evaluate``) → /slo +
          /metrics burn-rate/alert gauges;
        - ``TrainSupervisor`` (has ``train_snapshot``) → /train +
          /metrics ``paddle_tpu_train_resilience_*`` counters (its
          ``.tracer``, when set, is attached too);
        - ``Tracer`` / ``TrainMonitor`` (has ``events`` +
          ``prometheus_text``) → /metrics + /trace + liveness;
        - a serving engine (has ``prometheus_text``; its ``.tracer``, when
          set, is attached too) → /metrics (+ tracer surfaces).

        Every attached tracer additionally feeds the request-trace
        stitcher behind ``/requests`` and ``/request/<trace_id>``; an
        attached gateway also contributes its replicas' engine tracers,
        enumerated live at query time (drain-swapped replacements
        included), so ``attach(gateway)`` alone serves full stitched
        cross-replica timelines.
        """
        with self._lock:
            if hasattr(obj, "fleet_snapshot"):
                # FleetCollector: checked first — it also exposes
                # prometheus_text, and must not fall through to the
                # engine shape; its federation gauges still join /metrics
                base = name or f"fleet{len(self._fleets)}"
                self._fleets.append((base, obj))
                self._engines.append((base, obj))   # /metrics exposition
            elif hasattr(obj, "autoscaler_snapshot"):
                base = name or f"autoscaler{len(self._autoscalers)}"
                self._autoscalers.append((base, obj))
                self._engines.append((base, obj))   # /metrics exposition
            elif hasattr(obj, "add_objective") and hasattr(obj, "evaluate"):
                self._slos.append((name or f"slo{len(self._slos)}", obj))
            elif hasattr(obj, "gateway_snapshot"):
                base = name or f"gateway{len(self._gateways)}"
                self._gateways.append((base, obj))
                self._engines.append((base, obj))   # /metrics exposition
                tracer = getattr(obj, "tracer", None)
                if tracer is not None:
                    self._tracers.append((f"{base}.tracer", tracer))
            elif hasattr(obj, "tier_of") and hasattr(obj, "put"):
                # TieredKVStore: /kvstore + its gauges on /metrics
                self._kvstores.append(
                    (name or f"kvstore{len(self._kvstores)}", obj))
            elif hasattr(obj, "memory_snapshot"):
                # MemoryLedger: checked before the RunLedger shape — both
                # expose prometheus_text, only this one serves /memory
                self._memories.append(
                    (name or f"memory{len(self._memories)}", obj))
            elif hasattr(obj, "train_snapshot"):
                # TrainSupervisor: /train + its resilience counters on
                # /metrics (+ its tracer's surfaces)
                base = name or f"train{len(self._trains)}"
                self._trains.append((base, obj))
                self._engines.append((base, obj))   # /metrics exposition
                tracer = getattr(obj, "tracer", None)
                if tracer is not None:
                    self._tracers.append((f"{base}.tracer", tracer))
            elif hasattr(obj, "snapshot") and hasattr(obj, "record"):
                self._ledgers.append(
                    (name or f"ledger{len(self._ledgers)}", obj))
            elif hasattr(obj, "events") and hasattr(obj, "prometheus_text"):
                self._tracers.append(
                    (name or f"tracer{len(self._tracers)}", obj))
            elif hasattr(obj, "prometheus_text"):
                base = name or f"engine{len(self._engines)}"
                self._engines.append((base, obj))
                tracer = getattr(obj, "tracer", None)
                if tracer is not None:
                    self._tracers.append((f"{base}.tracer", tracer))
            else:
                raise TypeError(
                    f"unsupported ops-server source: {type(obj).__name__} "
                    f"(want a RunLedger, Tracer, TrainMonitor, or engine)")
        return self

    def heartbeat(self):
        """Explicit liveness tick for loops with no attached tracer."""
        self._last_beat = time.monotonic()

    # --------------------------------------------------------- lifecycle --
    def start(self) -> str:
        """Bind and serve on a daemon thread; returns the base URL
        (``port=0`` resolves to the ephemeral port actually bound)."""
        if self._httpd is not None:
            return self.url
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.ops = self                        # type: ignore[attr-defined]
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._started_at = time.monotonic()
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        daemon=True, name="ops-server")
        self._thread.start()
        self._log.info("ops server listening on %s", self.url)
        return self.url

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ----------------------------------------------------------- renders --
    def _sources(self):
        with self._lock:
            return (list(self._tracers), list(self._engines),
                    list(self._ledgers))

    def last_activity_age_s(self) -> float:
        """Seconds since the newest sign of life: an explicit heartbeat, or
        the latest event on any attached tracer/monitor (their ring
        timestamps are seconds on the tracer's own clock — ``now() - ts``
        is the event's age)."""
        tracers, _, _ = self._sources()
        age = time.monotonic() - self._last_beat
        for _name, tr in tracers:
            inner = getattr(tr, "tracer", tr)      # TrainMonitor wraps one
            try:
                if hasattr(inner, "last_event_age_s"):
                    ev_age = inner.last_event_age_s()   # O(1), no ring copy
                else:
                    evs = inner.events()
                    ev_age = (max(0.0, inner.now() - evs[-1]["ts"])
                              if evs else None)
                if ev_age is not None:
                    age = min(age, ev_age)
            except Exception as e:
                self._log.debug("ops server: activity scan failed on %s: "
                                "%r", _name, e)
        return age

    def _render_metrics(self) -> str:
        tracers, engines, ledgers = self._sources()
        with self._lock:
            slos = list(self._slos)
            kvstores = list(self._kvstores)
            memories = list(self._memories)
        parts = []
        for _name, obj in tracers + engines:
            parts.append(obj.prometheus_text())
        for _name, led in ledgers + memories:
            parts.append(led.prometheus_text())
        for _name, slo in slos:
            parts.append(slo.prometheus_text())
        for kname, store in kvstores:
            # namespaced per attachment so two attached stores cannot
            # collide in one exposition; the user-supplied name is
            # sanitized — one bad character would make the WHOLE
            # exposition unparseable, not just this store's family
            safe = re.sub(r"[^a-zA-Z0-9_]", "_", kname)
            parts.append(store.prometheus_text(
                namespace=f"paddle_tpu_kvstore_{safe}"))
        from .utils.stats import StatRegistry, prometheus_text as _pt
        parts.append(_pt(
            StatRegistry(), namespace="paddle_tpu_ops",
            extra_gauges={
                "uptime_seconds": time.monotonic() - self._started_at,
                "last_activity_age_seconds": self.last_activity_age_s(),
                "sources": len(tracers) + len(engines) + len(ledgers)}))
        return "".join(parts)

    def _render_healthz(self, run_probe: bool = False
                        ) -> Tuple[Dict[str, Any], bool]:
        age = self.last_activity_age_s()
        ok = age <= self.stall_threshold_s
        out: Dict[str, Any] = {
            "last_step_age_s": round(age, 3),
            "stall_threshold_s": self.stall_threshold_s,
            "stalled": not ok,
        }
        if run_probe:
            probe = compute_probe(self.probe_timeout_s)
            out["probe"] = probe
            ok = ok and bool(probe.get("ok"))
        out["ok"] = ok
        return out, ok

    def _render_ledger(self) -> Optional[Dict[str, Any]]:
        _, _, ledgers = self._sources()
        if not ledgers:
            return None
        if len(ledgers) == 1:
            return ledgers[0][1].snapshot()
        return {name: led.snapshot() for name, led in ledgers}

    def _render_memory(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            memories = list(self._memories)
        if not memories:
            return None
        if len(memories) == 1:
            return memories[0][1].memory_snapshot()
        return {name: ml.memory_snapshot() for name, ml in memories}

    def _render_gateway(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            gateways = list(self._gateways)
        if not gateways:
            return None
        if len(gateways) == 1:
            return gateways[0][1].gateway_snapshot()
        return {name: gw.gateway_snapshot() for name, gw in gateways}

    def _render_trace(self, n: int, kind: Optional[str]) -> Dict[str, Any]:
        tracers, _, _ = self._sources()
        n = max(1, min(int(n), 65536))
        events: Dict[str, List[Dict[str, Any]]] = {}
        for name, tr in tracers:
            evs = tr.events(kind) if kind else tr.events()
            events[name] = evs[-n:]
        return {"n": n, "kind": kind, "events": events}

    def _trace_index(self):
        """A fresh request-trace stitcher over every attached tracer —
        a pure pull reader of their bounded rings, so building one per
        request costs nothing beyond the scan it was going to do.

        Attached gateways contribute their CURRENT replicas' engine
        tracers, enumerated per query rather than snapshotted at
        ``attach()`` — a drain-swapped replacement replica shows up in
        ``/request/<id>`` without re-attaching anything."""
        from .telemetry import RequestTraceIndex
        tracers, _, _ = self._sources()
        with self._lock:
            gateways = list(self._gateways)
        seen = {id(tr) for _name, tr in tracers}
        for base, gw in gateways:
            enumerate_tracers = getattr(gw, "replica_tracers", None)
            if enumerate_tracers is None:
                continue
            for rname, tr in enumerate_tracers():
                if id(tr) not in seen:
                    seen.add(id(tr))
                    tracers.append((f"{base}.{rname}", tr))
        idx = RequestTraceIndex()
        for name, tr in tracers:
            try:
                idx.add_source(tr, name)
            except TypeError:
                pass                    # source without a usable ring
        return idx

    def _render_requests(self, n: int) -> Dict[str, Any]:
        n = max(1, min(int(n), 4096))
        return {"n": n, "requests": self._trace_index().recent(n)}

    def _render_request(self, trace_id: str) -> Optional[Dict[str, Any]]:
        if not trace_id:
            return None
        return self._trace_index().trace(trace_id)

    def _render_resilience(self) -> Optional[Dict[str, Any]]:
        """Resilience views of attached gateways; None when no attached
        gateway has a resilience policy (their ``resilience_snapshot``
        returns None)."""
        with self._lock:
            gateways = list(self._gateways)
        views = []
        for name, gw in gateways:
            snap_fn = getattr(gw, "resilience_snapshot", None)
            if snap_fn is None:
                continue
            snap = snap_fn()
            if snap is not None:
                views.append((name, snap))
        if not views:
            return None
        if len(views) == 1:
            return views[0][1]
        return dict(views)

    def _render_slo(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            slos = list(self._slos)
        if not slos:
            return None
        if len(slos) == 1:
            return slos[0][1].snapshot()
        return {name: slo.snapshot() for name, slo in slos}

    def _render_kvstore(self) -> Optional[Dict[str, Any]]:
        """KV-tiering views: every attached gateway with a live KV
        surface (roles, stores, or migration traffic) plus directly
        attached stores; None when nothing KV-tiered is attached."""
        with self._lock:
            gateways = list(self._gateways)
            kvstores = list(self._kvstores)
        views: Dict[str, Any] = {}
        for name, gw in gateways:
            snap_fn = getattr(gw, "kvstore_snapshot", None)
            surface = getattr(gw, "has_kv_surface", None)
            if snap_fn is None:
                continue
            if surface is not None and not surface():
                continue
            views[name] = snap_fn()
        for name, store in kvstores:
            views[name] = store.snapshot()
        if not views:
            return None
        if len(views) == 1:
            return next(iter(views.values()))
        return views

    def _render_autoscaler(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            autoscalers = list(self._autoscalers)
        if not autoscalers:
            return None
        if len(autoscalers) == 1:
            return autoscalers[0][1].autoscaler_snapshot()
        return {name: asc.autoscaler_snapshot()
                for name, asc in autoscalers}

    def _render_fleet(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            fleets = list(self._fleets)
        if not fleets:
            return None
        if len(fleets) == 1:
            return fleets[0][1].fleet_snapshot()
        return {name: fc.fleet_snapshot() for name, fc in fleets}

    def _render_train(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            trains = list(self._trains)
        if not trains:
            return None
        if len(trains) == 1:
            return trains[0][1].train_snapshot()
        return {name: sup.train_snapshot() for name, sup in trains}

    #: JSON routes a FleetCollector scrapes, mapped to their renderers —
    #: the in-process (server=) scrape path of ``render()``
    _RENDERS = {"/metrics": "_render_metrics",
                "/ledger": "_render_ledger",
                "/slo": "_render_slo",
                "/gateway": "_render_gateway",
                "/kvstore": "_render_kvstore",
                "/memory": "_render_memory",
                "/autoscaler": "_render_autoscaler",
                "/resilience": "_render_resilience",
                "/fleet": "_render_fleet",
                "/train": "_render_train"}

    def render(self, route: str):
        """Render one scrape surface WITHOUT a socket: the text
        exposition for ``/metrics``, the JSON payload (or ``None`` when
        nothing of that kind is attached — the 404 case) for the other
        scrapeable routes.  This is how a ``FleetCollector`` federates an
        in-process server (``add_target(name, server=srv)``) — bench and
        the sim fleet scrape unstarted servers through it, so no test or
        benchmark needs to bind a port to get fleet rollups."""
        fn = self._RENDERS.get(route)
        if fn is None:
            raise ValueError(f"unrenderable route {route!r} "
                             f"(want one of {sorted(self._RENDERS)})")
        return getattr(self, fn)()
