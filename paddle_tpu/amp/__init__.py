"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast:21, decorate:79, GradScaler
grad_scaler.py:26) + imperative/amp_auto_cast.cc white/black lists.

TPU-first: bf16 is the default mixed dtype (no loss scaling strictly needed —
bf16 has fp32's exponent range), but the fp16 GradScaler semantics
(found_inf, dynamic scaling) are implemented for parity and for fp16 use.
O1 = white-listed ops (matmul/conv family) compute in low precision; O2 =
whole model cast with fp32 master weights in the optimizer.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

# reference white/black lists (imperative/amp_auto_cast.cc / fp16_lists.py)
WHITE_LIST = {"conv2d", "matmul", "matmul_v2", "mul", "einsum", "linear", "conv1d",
              "conv3d", "attention"}
BLACK_LIST = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
              "cross_entropy", "layer_norm", "batch_norm", "reduce_sum", "erf"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white = set(WHITE_LIST)
        self.black = set(BLACK_LIST)
        self.version = 0  # bumped on every state change (snapshot cache key)


_state = _AmpState()


def amp_state():
    return _state


def amp_enabled() -> bool:
    return _state.enabled


def amp_dtype():
    return _state.dtype


def cast_if_amp(*arrays):
    """White-list op entry: cast float inputs to the amp dtype when active."""
    if not _state.enabled:
        return arrays
    dt = _state.dtype
    out = []
    for a in arrays:
        if a is not None and hasattr(a, "dtype") and \
                jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != dt:
            out.append(a.astype(dt))
        else:
            out.append(a)
    return tuple(out)


def blacklist_cast(*arrays):
    """Black-list op entry: promote low-precision floats back to fp32."""
    if not _state.enabled:
        return arrays
    out = []
    for a in arrays:
        if a is not None and hasattr(a, "dtype") and a.dtype in (jnp.float16,
                                                                 jnp.bfloat16):
            out.append(a.astype(jnp.float32))
        else:
            out.append(a)
    return tuple(out)


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None,
              level: str = "O1", dtype: str = "bfloat16"):
    """``paddle.amp.auto_cast`` parity."""
    prev = (_state.enabled, _state.dtype, _state.level, _state.white, _state.black)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    _state.white = (set(WHITE_LIST) | set(custom_white_list or ())) - \
        set(custom_black_list or ())
    _state.black = (set(BLACK_LIST) | set(custom_black_list or ())) - \
        set(custom_white_list or ())
    _state.version += 1
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.white,
         _state.black) = prev
        _state.version += 1


amp_guard = auto_cast


_capture_cache = {}


def capture_autocast():
    """Snapshot the current autocast state as a re-enterable context factory
    (used by the autograd tape so backward replay matches the forward).
    Cached per state version — recording N ops under one auto_cast block
    reuses a single factory."""
    ver = _state.version
    cached = _capture_cache.get(ver)
    if cached is not None:
        return cached
    enabled, dt, level = _state.enabled, _state.dtype, _state.level
    white, black = frozenset(_state.white), frozenset(_state.black)

    @contextlib.contextmanager
    def ctx():
        prev = (_state.enabled, _state.dtype, _state.level, _state.white,
                _state.black)
        _state.enabled, _state.dtype, _state.level = enabled, dt, level
        _state.white, _state.black = set(white), set(black)
        _state.version += 1
        try:
            yield
        finally:
            (_state.enabled, _state.dtype, _state.level, _state.white,
             _state.black) = prev
            _state.version += 1

    _capture_cache.clear()
    _capture_cache[ver] = ctx
    return ctx


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """``paddle.amp.decorate`` parity — O2 casts model params to low precision
    (the functional optimizer keeps fp32 master copies via multi_precision)."""
    dt = convert_dtype(dtype)
    models_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for m in models_list:
            m.astype(dt)
            # keep norms in fp32 (reference keeps bn/ln fp32 in pure-fp16 mode)
            from ..nn.layer.norm import _BatchNormBase, LayerNorm
            for sub in m.sublayers(include_self=True):
                if isinstance(sub, (_BatchNormBase, LayerNorm)):
                    sub._convert_dtype(jnp.float32)
    if optimizers is None:
        return models
    return models, optimizers


# the single blocking host transfer in GradScaler.unscale_ — a named hook so
# tests can assert the one-sync-per-step contract by counting calls
_host_bool = bool


class GradScaler:
    """Dynamic loss scaling (reference: grad_scaler.py:26 + fluid/dygraph/amp
    AmpScaler; kernels amp/check_finite_and_unscale_op, update_loss_scaling_op).

    Telemetry: when a ``telemetry.TrainMonitor`` is active
    (``set_active_monitor`` / ``TelemetryCallback``), ``unscale_`` emits a
    ``found_inf`` event and ``update()`` a ``scale_change`` event."""

    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000, decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._already_unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._already_unscaled:
            return
        self._already_unscaled = True
        inv = 1.0 / self._scale
        # one pass: unscale each grad in place (at most ONE transient fp32
        # copy live at a time — stacking all fp32 copies first would spike
        # peak HBM) keeping only a scalar finite flag per grad; then ONE
        # stacked reduction and ONE host sync for the whole parameter list
        # (the old per-param bool() loop blocked the device once per param)
        flags = []
        for p in (optimizer._parameter_list or []):
            if p._grad is None:
                continue
            g = p._grad.astype(jnp.float32) * inv
            flags.append(jnp.isfinite(g).all())
            p._grad = g.astype(p._grad.dtype)
        found = bool(flags) and not _host_bool(jnp.stack(flags).all())
        self._found_inf = found
        self._emit_telemetry(found)

    def step(self, optimizer):
        """Unscale (if not already) and apply the optimizer step unless a
        non-finite gradient was found.  Like the reference, ``update()`` is a
        separate call (minimize() chains both)."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def _emit_telemetry(self, found_inf: bool):
        from ..telemetry import current_monitor
        mon = current_monitor()
        if mon is not None:
            mon.observe_scaler(self._scale, found_inf)

    def update(self):
        self._already_unscaled = False
        if not (self._enable and self._dynamic):
            return
        old_scale = self._scale
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        if self._scale != old_scale:
            self._emit_telemetry(False)

    # ------------------------------------------------------- functional form
    def init_state(self):
        return {"scale": jnp.asarray(self._scale, jnp.float32),
                "good": jnp.zeros([], jnp.int32), "bad": jnp.zeros([], jnp.int32)}

    def functional_update(self, state, grads):
        """Pure: unscale grads, compute found_inf, new scaler state.

        Returns (unscaled_grads, found_inf, new_state) — usable inside jit
        (≙ check_finite_and_unscale + update_loss_scaling ops fused into the
        step program)."""
        inv = 1.0 / state["scale"]
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        finite = jax.tree_util.tree_reduce(
            lambda acc, g: acc & jnp.isfinite(g).all(), unscaled,
            jnp.asarray(True))
        found_inf = ~finite
        good = jnp.where(found_inf, 0, state["good"] + 1)
        bad = jnp.where(found_inf, state["bad"] + 1, 0)
        scale = state["scale"]
        scale = jnp.where(bad >= self._decr_every_n,
                          jnp.maximum(scale * self._decr_ratio, 1.0), scale)
        bad = jnp.where(bad >= self._decr_every_n, 0, bad)
        scale = jnp.where(good >= self._incr_every_n_steps,
                          scale * self._incr_ratio, scale)
        good = jnp.where(good >= self._incr_every_n_steps, 0, good)
        return unscaled, found_inf, {"scale": scale, "good": good, "bad": bad}

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return jax.default_backend() != "cpu"
