"""Goodput ledger: exhaustive wall-clock attribution for one run.

The operator's first question about any training or serving run is *what
fraction of wall time was useful compute, and where did the rest go?*  The
PR 2/4 telemetry (``telemetry.Tracer`` / ``TrainMonitor``) records the
individual events — ticks, step dispatch, device-blocked loss fetches,
compiles — but nothing folds them into an answer.  :class:`RunLedger` does:
it partitions a run's elapsed wall clock **exhaustively** into
non-overlapping buckets

==================== =====================================================
bucket               wall time spent …
==================== =====================================================
``compute``          device-blocked (the host waited on device results:
                     the hapi loss fetch, ``_run_timed``'s sync, a
                     serving scheduler tick)
``data_wait``        blocked on the input pipeline (DataLoader
                     ``__next__``, ``reader.buffered`` queue waits)
``host_dispatch``    host-side step dispatch wall (Python + program launch
                     — the step chain itself is async)
``compile``          trace + XLA compile + first dispatch of a program
``checkpoint_save``  writing a checkpoint (``framework.io.save``,
                     ``distributed.checkpoint.save`` synchronous part)
``checkpoint_restore`` reading one back
``comm``             host-level collective exchanges
                     (``fleet.metrics.all_reduce_metrics``)
``eval``             inside ``Model.evaluate`` (an exclusive span —
                     nested data/fetch waits fold into it)
``unattributed``     the remainder — elapsed minus everything above
==================== =====================================================

Buckets sum to elapsed wall time by construction (``unattributed`` is the
remainder; over-attribution is surfaced as ``overflow_s`` instead of being
hidden), and ``goodput = compute / elapsed``.  Producers are the existing
telemetry event stream — ``Tracer.set_ledger`` forwards tick/compile/
train_step/sync durations with one attribute check — plus the
instrumentation seams in ``io/``, ``reader.py``, ``framework/io.py``,
``distributed/checkpoint.py`` and ``fleet/metrics``, which report through
the process-wide active ledger (:func:`set_active_ledger` /
:func:`current_ledger`, the ``set_active_monitor`` convention).  Everything
is zero-cost when no ledger is active: one ``is None`` check per seam.

Cross-host: :meth:`RunLedger.aggregate` reuses
``fleet.metrics.all_reduce_metrics`` — ONE batched collective per reduction
op — for global goodput and per-bucket straggler skew (max replica seconds
over the mean), mirroring ``TrainMonitor.aggregate``.

The :class:`FlightRecorder` closes the post-mortem gap: all of this state
lives in process memory and dies with it.  Installed, it dumps the tracer
ring buffers, the ledger snapshot, and every thread's stack to a crash
directory on abnormal exit (unhandled exception, SIGTERM, or a hard fault
via ``faulthandler``), so the last N seconds of events survive the crash.

No single reference counterpart: this is the goodput/badput accounting of
large-fleet training reports (stall attribution in MPMD pipeline scaling,
arXiv:2412.14374) composed with the reference profiler's state-dump role.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import faulthandler
import json
import logging
import os
import signal as _signal
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["RunLedger", "FlightRecorder", "BUCKETS", "set_active_ledger",
           "current_ledger", "ledger_span", "chrome_counters_from_dump"]

#: The exhaustive bucket taxonomy, in display order.  ``unattributed`` is
#: derived (elapsed − attributed), never recorded directly.
BUCKETS: Tuple[str, ...] = (
    "compute", "data_wait", "host_dispatch", "compile", "checkpoint_save",
    "checkpoint_restore", "comm", "eval", "unattributed")

_ATTRIBUTED = tuple(b for b in BUCKETS if b != "unattributed")

_EPS = 1e-12


class RunLedger:
    """Exhaustive wall-clock attribution for one run (module docstring).

    ``capacity`` bounds the retained ``(ts, bucket, dur)`` sample series
    (the chrome counter track / flight-recorder payload); the per-bucket
    totals are exact regardless.  All mutation is under one lock;
    ``record`` is a dict add + deque append — cheap enough for per-batch
    seams, and seams only reach it when a ledger is active.
    """

    def __init__(self, capacity: int = 4096,
                 logger: Optional[logging.Logger] = None,
                 clock: Optional[Callable[[], float]] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._lock = threading.Lock()
        # injectable clock so sim hosts attribute against SIM elapsed time
        # (a real-clock denominator under sim-second compute makes goodput
        # meaningless); real runs keep time.monotonic
        self._clock = time.monotonic if clock is None else clock
        self._t0 = self._clock()
        self._closed_at: Optional[float] = None
        self._sec: Dict[str, float] = {b: 0.0 for b in _ATTRIBUTED}
        self._n: Dict[str, int] = {b: 0 for b in _ATTRIBUTED}
        self._series: collections.deque = collections.deque(maxlen=capacity)
        self._tls = threading.local()      # per-thread exclusive-span stack
        self._prev_active: Optional["RunLedger"] = None
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)

    # ------------------------------------------------------------- clock --
    def now(self) -> float:
        return self._clock() - self._t0

    def elapsed_s(self) -> float:
        if self._closed_at is not None:
            return self._closed_at - self._t0
        return self._clock() - self._t0

    def close(self):
        """Freeze elapsed time (idempotent).  Later ``record`` calls are
        dropped — the run is over; a closed ledger is a stable artifact."""
        with self._lock:
            if self._closed_at is None:
                self._closed_at = self._clock()

    def reset(self):
        """Clear all attribution and restart the elapsed clock — what
        ``GoodputCallback`` does at train begin so ``elapsed`` measures
        exactly the fit window, not construction-to-fit dead time."""
        with self._lock:
            self._t0 = self._clock()
            self._closed_at = None
            self._sec = {b: 0.0 for b in _ATTRIBUTED}
            self._n = {b: 0 for b in _ATTRIBUTED}
            self._series.clear()

    # ------------------------------------------------------------ ingest --
    def record(self, bucket: str, dur_s: float, count: int = 1):
        """Attribute ``dur_s`` seconds of wall clock to ``bucket``.

        Inside an *exclusive* span (see :meth:`span`) records for OTHER
        buckets on the same thread are absorbed — their wall time is
        already covered by the enclosing span, and double-attribution
        would break the buckets-sum-to-elapsed invariant."""
        # validate against the static schema, not the live dict — reading
        # self._sec here would race reset()'s locked rebind of it
        if bucket not in _ATTRIBUTED:
            raise ValueError(f"unknown bucket {bucket!r}; one of {_ATTRIBUTED}")
        excl = getattr(self._tls, "exclusive", None)
        if excl and excl[-1] != bucket:
            return
        if dur_s < 0.0:
            dur_s = 0.0
        with self._lock:
            if self._closed_at is not None:
                return
            self._sec[bucket] += dur_s
            self._n[bucket] += count
            self._series.append((self._clock() - self._t0, bucket, dur_s))

    @contextlib.contextmanager
    def span(self, bucket: str, exclusive: bool = False):
        """Context manager attributing the block's wall time to ``bucket``.
        ``exclusive=True`` additionally absorbs same-thread records for
        other buckets inside the block (``Model.evaluate`` uses it: the
        eval loop's data waits and fetches ARE eval time)."""
        # validate against the static schema, not the live dict — reading
        # self._sec here would race reset()'s locked rebind of it
        if bucket not in _ATTRIBUTED:
            raise ValueError(f"unknown bucket {bucket!r}; one of {_ATTRIBUTED}")
        if exclusive:
            stack = getattr(self._tls, "exclusive", None)
            if stack is None:
                stack = self._tls.exclusive = []
            stack.append(bucket)
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            dur = time.perf_counter() - t0
            if exclusive:
                self._tls.exclusive.pop()
            self.record(bucket, dur)

    # ----------------------------------------------------------- queries --
    def snapshot(self) -> Dict[str, Any]:
        """One JSON-able snapshot.  Invariant: ``sum(buckets_s.values())``
        equals ``elapsed_s`` whenever ``overflow_s`` is 0 (and exceeds it
        by exactly ``overflow_s`` otherwise — over-attribution is shown,
        never silently clipped into a lie)."""
        with self._lock:
            sec = dict(self._sec)
            counts = dict(self._n)
        elapsed = self.elapsed_s()
        attributed = sum(sec.values())
        unattributed = max(0.0, elapsed - attributed)
        overflow = max(0.0, attributed - elapsed)
        buckets = dict(sec, unattributed=unattributed)
        denom = max(elapsed, _EPS)
        return {
            "elapsed_s": elapsed,
            "goodput": sec["compute"] / denom,
            "buckets_s": buckets,
            "fractions": {b: v / denom for b, v in buckets.items()},
            "counts": counts,
            "overflow_s": overflow,
            "closed": self._closed_at is not None,
        }

    def goodput(self) -> float:
        return self.snapshot()["goodput"]

    def aggregate(self) -> Dict[str, Any]:
        """Cross-host roll-up via ``fleet.metrics.all_reduce_metrics`` —
        ONE batched collective per reduction op (sum + max), never one per
        bucket: global goodput (fleet compute seconds over fleet elapsed
        seconds) and per-bucket straggler skew (max replica seconds over
        the mean; 1.0 = perfectly balanced, None = bucket empty
        everywhere).  Identity in a single process."""
        from .distributed import env
        from .distributed.fleet.metrics.metric import all_reduce_metrics

        snap = self.snapshot()
        local = {b: float(snap["buckets_s"][b]) for b in BUCKETS}
        local["elapsed_s"] = float(snap["elapsed_s"])
        sums = all_reduce_metrics(local, "sum")
        maxs = all_reduce_metrics(local, "max")
        world = max(int(env.get_world_size()), 1)
        skew = {}
        for b in BUCKETS:
            mean = sums[b] / world
            skew[b] = (maxs[b] / mean) if mean > _EPS else None
        return {
            "world": world,
            "goodput": sums["compute"] / max(sums["elapsed_s"], _EPS),
            "buckets_s": {b: sums[b] for b in BUCKETS},
            "elapsed_s_max": maxs["elapsed_s"],
            "straggler_skew": skew,
        }

    # ----------------------------------------------------------- exports --
    def prometheus_text(self, namespace: str = "paddle_tpu_ledger") -> str:
        """Text exposition of the snapshot: per-bucket second gauges,
        ``goodput``, ``elapsed_seconds``, ``overflow_seconds``, and
        per-bucket event counters — what ``ops_server`` merges into
        ``GET /metrics``."""
        from .utils.stats import StatRegistry, prometheus_text as _pt
        snap = self.snapshot()
        gauges = {"goodput": snap["goodput"],
                  "elapsed_seconds": snap["elapsed_s"],
                  "overflow_seconds": snap["overflow_s"]}
        for b, v in snap["buckets_s"].items():
            gauges[f"{b}_seconds"] = v
        counters = {f"{b}_events": n for b, n in snap["counts"].items()}
        return _pt(StatRegistry(), namespace=namespace,
                   extra_gauges=gauges, extra_counters=counters)

    def to_dict(self) -> Dict[str, Any]:
        """Snapshot + retained sample series — the ``dump_json`` payload
        and the flight-recorder artifact."""
        with self._lock:
            series = [[ts, b, dur] for ts, b, dur in self._series]
        return {"kind": "ledger", "snapshot": self.snapshot(),
                "series": series}

    def dump_json(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)

    def to_chrome_counters(self, pid: str = "paddle_tpu.ledger"
                           ) -> List[Dict[str, Any]]:
        """Chrome-trace counter ("C") events: the cumulative per-bucket
        seconds after each retained sample — a stacked counter track that
        merges next to the tracer's span rows in Perfetto
        (``tools/trace_to_chrome.py --ledger``)."""
        return chrome_counters_from_dump(self.to_dict(), pid=pid)

    # ---------------------------------------------------------- lifecycle --
    def activate(self) -> "RunLedger":
        """Install as the process-wide active ledger (the seam the io/
        reader/checkpoint/comm instrumentation reports through).  Also a
        context manager."""
        self._prev_active = set_active_ledger(self)
        return self

    def deactivate(self):
        set_active_ledger(self._prev_active)
        self._prev_active = None

    __enter__ = activate

    def __exit__(self, *exc):
        self.deactivate()
        return False


def chrome_counters_from_dump(data: Dict[str, Any],
                              pid: str = "paddle_tpu.ledger"
                              ) -> List[Dict[str, Any]]:
    """``RunLedger.to_dict()`` / ``dump_json`` payload → chrome counter
    events (offline twin of ``to_chrome_counters``, used by
    ``tools/trace_to_chrome.py --ledger``)."""
    out: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": pid}}]
    cum = {b: 0.0 for b in _ATTRIBUTED}
    for ts, bucket, dur in data.get("series", []):
        if bucket in cum:
            cum[bucket] += dur
        out.append({"name": "ledger_seconds", "ph": "C", "pid": pid,
                    "ts": float(ts) * 1e6,
                    "args": {b: round(v, 6) for b, v in cum.items()}})
    return out


# --------------------------------------------------------------------------
# process-wide active ledger
# --------------------------------------------------------------------------

_active_ledger: Optional[RunLedger] = None


def set_active_ledger(ledger: Optional[RunLedger]) -> Optional[RunLedger]:
    """Install the process-wide active ledger (or None) and return the
    previous one.  Seams that cannot be threaded a handle — the DataLoader
    iterators, ``reader.buffered``, checkpoint save/load, the fleet metric
    collective — report through this; everything else takes an explicit
    ledger."""
    global _active_ledger
    prev = _active_ledger
    _active_ledger = ledger
    return prev


def current_ledger() -> Optional[RunLedger]:
    return _active_ledger


@contextlib.contextmanager
def ledger_span(bucket: str, exclusive: bool = False):
    """``span`` on the active ledger; a no-op context when none is active
    (the one-check-zero-cost contract every seam shares)."""
    led = _active_ledger
    if led is None:
        yield None
        return
    with led.span(bucket, exclusive=exclusive):
        yield led


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

class FlightRecorder:
    """Crash-dump hook: on abnormal exit, write the attached tracers' ring
    buffers, the attached ledgers' snapshots, and every thread's stack to
    ``crash_dir`` — the post-mortem keeps the last N seconds of events
    instead of dying with the process.

    Three triggers, all installed by :meth:`install`:

    - **unhandled exception** — chains ``sys.excepthook`` (dump first,
      then the previous hook prints the traceback as usual);
    - **signals** (default SIGTERM, the preemption/oom-killer notice) —
      dump, then chain the previous handler (or re-raise the default so
      the process still dies with the right status);
    - **hard faults** — ``faulthandler.enable`` onto a file in the crash
      dir, so segfaults/deadlock ``SIGABRT`` leave native-level stacks the
      Python hooks can never see.

    ``dump()`` never raises (a crash handler that crashes destroys the
    evidence it exists to preserve); every failure is logged and skipped.
    ``uninstall()`` restores all hooks — tests rely on it.
    """

    def __init__(self, crash_dir: str, sources=(),
                 logger: Optional[logging.Logger] = None):
        self.crash_dir = str(crash_dir)
        # dump() runs on signal/excepthook paths while the main thread may
        # still be attaching sources; the lock is held only for list ops,
        # never across a source dump, so the crash path can't deadlock
        self._sources_lock = threading.Lock()
        self._sources: List[Tuple[str, Any]] = []  # guarded-by: _sources_lock
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._installed = False
        self._prev_excepthook = None
        self._prev_signals: Dict[int, Any] = {}
        self._fh_file = None
        self._dumped = False
        # pinned bound methods: attribute access creates a FRESH bound
        # method each time, so identity checks against self._excepthook
        # would never match what was installed
        self._hook = self._excepthook
        self._sig_hook = self._signal_handler
        for src in sources:
            self.add_source(src)

    def add_source(self, obj, name: Optional[str] = None) -> "FlightRecorder":
        """Attach a dump source: a ``Tracer``/``TrainMonitor`` (anything
        with ``dump_jsonl``), a ``RunLedger``, ``telemetry_memory
        .MemoryLedger`` or ``telemetry_fleet.FleetCollector``
        (``to_dict`` — ``add_source(collector, "fleet")`` makes the dump
        carry ``fleet.json``: the last fleet snapshot plus the spool
        tail, so a post-mortem shows what the REST of the fleet looked
        like when this process died), or a ``ServingGateway``
        (``gateway_snapshot`` — the dump then carries replica/queue state
        and, with a resilience policy, the breaker and brownout state the
        crash happened under).  Sources exposing ``forensics()`` (the
        memory ledger) additionally get an OOM-forensics section —
        ``<name>-forensics.json`` with top pools, recent growth, and the
        largest live arrays with tree paths."""
        if not (hasattr(obj, "dump_jsonl") or hasattr(obj, "to_dict")
                or hasattr(obj, "gateway_snapshot")):
            raise TypeError(f"unsupported flight-recorder source: {obj!r}")
        with self._sources_lock:
            self._sources.append((name or f"{type(obj).__name__.lower()}"
                                  f"{len(self._sources)}", obj))
        return self

    # ------------------------------------------------------------- hooks --
    def install(self, signals=(_signal.SIGTERM,),
                enable_faulthandler: bool = True) -> "FlightRecorder":
        if self._installed:
            return self
        os.makedirs(self.crash_dir, exist_ok=True)
        if enable_faulthandler:
            try:
                self._fh_file = open(
                    os.path.join(self.crash_dir, "faulthandler.log"), "a")
                faulthandler.enable(file=self._fh_file)
            except (OSError, RuntimeError) as e:
                self._log.warning("flight recorder: faulthandler not "
                                  "enabled: %s", e)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._hook
        for sig in signals:
            try:
                self._prev_signals[sig] = _signal.signal(
                    sig, self._sig_hook)
            except (ValueError, OSError) as e:
                # not the main thread, or an unblockable signal — the other
                # triggers still cover the exit
                self._log.warning("flight recorder: cannot hook signal "
                                  "%s: %s", sig, e)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        if sys.excepthook is self._hook:
            sys.excepthook = self._prev_excepthook
        for sig, prev in self._prev_signals.items():
            try:
                if _signal.getsignal(sig) is self._sig_hook:
                    _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_signals.clear()
        if self._fh_file is not None:
            try:
                faulthandler.disable()
                self._fh_file.close()
            except (OSError, RuntimeError):
                pass
            self._fh_file = None
        self._installed = False

    def _excepthook(self, exc_type, exc, tb):
        self.dump(f"unhandled {exc_type.__name__}: {exc}", _auto=True)
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _signal_handler(self, signum, frame):
        self.dump(f"signal {_signal.Signals(signum).name}", _auto=True)
        prev = self._prev_signals.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev != _signal.SIG_IGN:
            # restore the default disposition and re-raise so the process
            # exits with the conventional signal status
            _signal.signal(signum, _signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    # -------------------------------------------------------------- dump --
    def dump(self, reason: str = "manual", _auto: bool = False
             ) -> Optional[str]:
        """Write one crash dump; returns its directory (or None when the
        dump itself failed).  Only the FIRST automatic trigger dumps (an
        excepthook and a signal firing for the same death must not
        overwrite each other); manual calls always dump, each into its
        own directory."""
        if _auto and self._dumped:
            return None
        try:
            stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
            base = os.path.join(self.crash_dir,
                                f"crash-{stamp}-{os.getpid()}")
            out = base
            n = 1
            while os.path.exists(out):    # same-second dumps get own dirs
                out = f"{base}-{n}"
                n += 1
            os.makedirs(out, exist_ok=True)
            meta = {"reason": reason, "pid": os.getpid(),
                    "time_unix": time.time(),
                    "argv": list(sys.argv)}
            with open(os.path.join(out, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2)
            with open(os.path.join(out, "threads.txt"), "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            with self._sources_lock:      # snapshot; dump outside the lock
                sources = list(self._sources)
            for name, src in sources:
                try:
                    if hasattr(src, "dump_jsonl"):
                        src.dump_jsonl(os.path.join(out, f"{name}.jsonl"))
                    elif hasattr(src, "gateway_snapshot"):
                        with open(os.path.join(out, f"{name}.json"),
                                  "w") as f:
                            json.dump(src.gateway_snapshot(), f)
                    elif hasattr(src, "to_dict"):
                        with open(os.path.join(out, f"{name}.json"),
                                  "w") as f:
                            json.dump(src.to_dict(), f)
                    if hasattr(src, "forensics"):
                        # the OOM post-mortem section: small, human-first
                        # (top pools / recent growth / largest arrays),
                        # separate from the full series payload above
                        with open(os.path.join(
                                out, f"{name}-forensics.json"), "w") as f:
                            json.dump(src.forensics(), f, indent=2)
                except Exception as e:
                    self._log.warning("flight recorder: source %s failed "
                                      "to dump: %s", name, e)
            self._dumped = True
            self._log.warning("flight recorder: dumped %d source(s) to %s "
                              "(%s)", len(sources), out, reason)
            return out
        except Exception as e:
            self._log.warning("flight recorder: dump failed: %s", e)
            return None

    # a module-level convenience: install-and-forget with atexit cleanup of
    # the faulthandler file handle (NOT an exit dump — normal exits are not
    # crashes; the excepthook/signal triggers decide abnormality)
    @classmethod
    def install_default(cls, crash_dir: str, sources=()) -> "FlightRecorder":
        fr = cls(crash_dir, sources=sources).install()
        atexit.register(fr.uninstall)
        return fr
