"""Metrics (reference: python/paddle/metric/metrics.py — Metric, Accuracy,
Precision, Recall, Auc)."""

from __future__ import annotations

import abc
from typing import List

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(getattr(x, "_data", x))


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = np.atleast_1d(_np(label))
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim and label.shape[-1] != 1:
            label = label.argmax(-1)
        label = label.reshape(label.shape[0], -1)[:, :1]
        correct = (order == label).astype("float32")
        return Tensor(correct)

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(float(num) / max(correct.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        den = self.tp + self.fp
        return self.tp / den if den else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        den = self.tp + self.fn
        return self.tp / den if den else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via threshold-bucket histograms (reference metric: auc_op.h).

    Note: like the reference kernel, this is the *histogram approximation* —
    scores are bucketed into ``num_thresholds`` bins and the trapezoid rule
    runs over bin boundaries, so ties within a bin are averaged.  With the
    default 4095 thresholds the deviation from exact rank-based AUC is
    < 1/4095; raise ``num_thresholds`` for more resolution.
    """

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos = preds[:, 1]
        else:
            pos = preds.reshape(-1)
        bins = np.minimum((pos * self.num_thresholds).astype("int64"),
                          self.num_thresholds - 1)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds high→low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    hit = (order == lab[:, None]).any(1).mean()
    return Tensor(np.asarray(hit, dtype="float32"))
