from .export import export  # noqa: F401

__all__ = ["export"]
