"""ONNX export (reference: python/paddle/onnx/export.py).

The reference delegates entirely to the external ``paddle2onnx`` package;
the in-tree function is a thin dispatcher.  Same here: ONNX emission needs
an external converter that this zero-dependency build does not ship, so
the function raises with a pointer to the supported interchange format —
the StableHLO artifact written by ``paddle.jit.save`` (loadable from any
XLA frontend).
"""

from __future__ import annotations

from typing import Optional, Sequence


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 9, **configs):
    """Export ``layer`` for external inference runtimes.

    Mirrors the reference signature (onnx/export.py).  Requires the
    ``onnx`` package for true ``.onnx`` output; otherwise raises with a
    pointer to the StableHLO export path (``paddle.jit.save``), which is
    the supported interchange format of this TPU build.
    """
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ModuleNotFoundError(
            "ONNX export needs the 'onnx' package, which is not available "
            "in this build. Use paddle.jit.save(layer, path, input_spec=...) "
            "to export a portable StableHLO artifact instead (loadable via "
            "paddle.jit.load or any XLA-based runtime).") from None
    # onnx available: lower through jax's ONNX-less route is not provided by
    # jax itself; go via the saved StableHLO + onnx's converter when present.
    raise NotImplementedError(
        "Direct ONNX emission is not implemented; export StableHLO via "
        "paddle.jit.save and convert externally.")
