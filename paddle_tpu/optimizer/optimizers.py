"""Concrete optimizers.

Reference update rules: operators/optimizers/{sgd,momentum,adam,adamw,lamb,
adagrad,adadelta,rmsprop}_op.* and python/paddle/optimizer/*.py — the math
matches the reference kernels exactly (loss-parity oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _rule(self, p, g, slots, lr, step=None):
        return p - lr.astype(p.dtype) * g, slots


class Momentum(Optimizer):
    """Reference: momentum_op.h — supports nesterov + (optional) LARS-free path."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None):
        mu = jnp.asarray(self._momentum, p.dtype)
        v = slots["velocity"] * mu + g
        if self._nesterov:
            new_p = p - lr.astype(p.dtype) * (g + mu * v)
        else:
            new_p = p - lr.astype(p.dtype) * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """Reference: adam_op.h AdamFunctor — lr_t = lr*sqrt(1-b2^t)/(1-b1^t)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None):
        b1 = jnp.asarray(self._beta1, jnp.float32)
        b2 = jnp.asarray(self._beta2, jnp.float32)
        t = step.astype(jnp.float32)
        m = b1.astype(p.dtype) * slots["moment1"] + (1 - b1).astype(p.dtype) * g
        v = b2.astype(p.dtype) * slots["moment2"] + (1 - b2).astype(p.dtype) * (g * g)
        lr_t = lr * jnp.sqrt(1 - jnp.power(b2, t)) / (1 - jnp.power(b1, t))
        denom = jnp.sqrt(v.astype(jnp.float32)) + self._epsilon
        new_p = p - (lr_t * m.astype(jnp.float32) / denom).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: adamw_op — p *= (1 - lr*coeff))."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        from ..regularizer import L2Decay
        wd = weight_decay if not isinstance(weight_decay, float) else L2Decay(weight_decay)
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, wd,
                         grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _rule(self, p, g, slots, lr, step=None):
        coeff = self._weight_decay.coeff if self._weight_decay is not None else 0.0
        p = p * (1 - lr.astype(p.dtype) * jnp.asarray(coeff, p.dtype))
        return super()._rule(p, g, slots, lr, step=step)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None):
        b1 = jnp.asarray(self._beta1, p.dtype)
        b2 = jnp.asarray(self._beta2, p.dtype)
        t = step.astype(jnp.float32)
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g) + self._epsilon)
        lr_t = (lr / (1 - jnp.power(b1.astype(jnp.float32), t))).astype(p.dtype)
        new_p = p - lr_t * m / u
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._epsilon = epsilon
        self._init_value = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_value)}

    def _rule(self, p, g, slots, lr, step=None):
        moment = slots["moment"] + g * g
        new_p = p - lr.astype(p.dtype) * g / (jnp.sqrt(moment) + self._epsilon)
        return new_p, {"moment": moment}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._epsilon, self._rho = epsilon, rho

    def _init_slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None):
        rho = jnp.asarray(self._rho, p.dtype)
        eps = jnp.asarray(self._epsilon, p.dtype)
        sg = rho * slots["avg_squared_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt(slots["avg_squared_update"] + eps) / jnp.sqrt(sg + eps) * g
        su = rho * slots["avg_squared_update"] + (1 - rho) * update * update
        return p + lr.astype(p.dtype) * update, \
            {"avg_squared_grad": sg, "avg_squared_update": su}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        slots = {"mean_square": jnp.zeros_like(p), "momentum": jnp.zeros_like(p)}
        if self._centered:
            slots["mean_grad"] = jnp.zeros_like(p)
        return slots

    def _rule(self, p, g, slots, lr, step=None):
        rho = jnp.asarray(self._rho, p.dtype)
        ms = rho * slots["mean_square"] + (1 - rho) * g * g
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._epsilon)
        mom = jnp.asarray(self._momentum, p.dtype) * slots["momentum"] + \
            lr.astype(p.dtype) * g / denom
        out = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            out["mean_grad"] = mg
        return p - mom, out


class Lamb(Optimizer):
    """Reference: lamb_op.h — layerwise trust ratio * adam update."""

    _per_tensor_norms = True

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None):
        b1 = jnp.asarray(self._beta1, jnp.float32)
        b2 = jnp.asarray(self._beta2, jnp.float32)
        t = step.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = b1 * slots["moment1"].astype(jnp.float32) + (1 - b1) * g32
        v = b2 * slots["moment2"].astype(jnp.float32) + (1 - b2) * g32 * g32
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        update = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + self._lamb_wd * p32
        p_norm = jnp.linalg.norm(p32)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
        new_p = p32 - lr * trust * update
        return new_p.astype(p.dtype), {"moment1": m.astype(p.dtype),
                                       "moment2": v.astype(p.dtype)}


class Lars(Optimizer):
    """LARS momentum (reference: operators/optimizers/lars_momentum_op.h,
    fluid LarsMomentumOptimizer).

    local_lr = lr * lars_coeff * ||p|| / (||g|| + wd * ||p|| + eps)
    v        = mu * v + local_lr * (g + wd * p);   p -= v
    The trust-ratio guard (||p|| > 0 and ||g|| > 0) keeps fresh zero-init
    tensors on the plain momentum path, as the CUDA kernel does.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, multi_precision=False, name=None,
                 exclude_from_weight_decay=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    _wants_param_name = True
    _per_tensor_norms = True

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step=None, param_name=None):
        mu = jnp.asarray(self._momentum, jnp.float32)
        wd = self._lars_wd
        if param_name is not None and any(
                ex in str(param_name) for ex in self._exclude):
            wd = 0.0  # reference: exclude_from_weight_decay name substrings
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        p_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * p_norm
            / (g_norm + wd * p_norm + self._epsilon),
            lr)
        v = mu * slots["velocity"].astype(jnp.float32) \
            + local_lr * (g32 + wd * p32)
        new_p = p32 - v
        return new_p.astype(p.dtype), {"velocity": v.astype(p.dtype)}


LarsMomentum = Lars
