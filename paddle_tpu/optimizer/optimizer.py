"""Optimizer base.

Reference: python/paddle/optimizer/optimizer.py (Optimizer) +
operators/optimizers/*.  Each optimizer defines one pure update rule
``_rule(param, grad, slots, lr) -> (new_param, new_slots)`` used by BOTH:

- the eager path (``step()`` reads ``p._grad`` and mutates ``p._data``), and
- the functional path (``init_state``/``update`` over pytrees) that the jit
  train step, hapi Model and fleet distributed optimizers consume.  On TPU
  the functional path is the performant one: the whole update fuses into the
  step program, and states inherit param shardings (ZeRO = resharding this
  state pytree).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        from .lr import LRScheduler
        self._parameter_list: Optional[List[Parameter]] = (
            list(parameters) if parameters is not None else None)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, float):
            from ..regularizer import L2Decay
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        # eager accumulators: slot_name -> {id(param): array}
        self._accum: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0

    # ------------------------------------------------------------------ LR
    def get_lr(self) -> float:
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when learning rate is a scheduler")
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # --------------------------------------------------------------- eager
    def _slots_for(self, p: Parameter) -> Dict[str, Any]:
        key = id(p)
        if key not in self._accum:
            self._accum[key] = self._init_slots(p._data)
        return self._accum[key]

    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer created without a parameter list; "
                             "pass parameters= or use the functional API")
        lr = self.get_lr()
        grads = {id(p): p._grad for p in params}
        if self._grad_clip is not None:
            grads = self._grad_clip._clip_eager(params, grads)
        self._step_count += 1
        for p in params:
            g = grads.get(id(p))
            if g is None or not p.trainable:
                continue
            g = g.astype(p._data.dtype) if g.dtype != p._data.dtype else g
            if self._weight_decay is not None and self._use_coupled_wd(p):
                g = g + self._weight_decay.grad_term(p._data).astype(g.dtype)
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            slots = self._slots_for(p)
            extra = {"param_name": getattr(p, "name", None)} \
                if self._wants_param_name else {}
            new_p, new_slots = self._rule(p._data, g, slots, jnp.asarray(plr, jnp.float32),
                                          step=jnp.asarray(self._step_count, jnp.int32),
                                          **extra)
            p._data = new_p
            self._accum[id(p)] = new_slots

    minimize_step = step

    _decoupled_wd = False  # AdamW-style decoupled decay overrides to True
    # subclasses whose rule needs the parameter's identity (e.g. Lars
    # exclude_from_weight_decay) set this; the rule then receives
    # ``param_name`` (Parameter.name eagerly, the pytree key functionally)
    _wants_param_name = False
    # subclasses whose rule reduces over the WHOLE parameter tensor (Lamb/
    # Lars trust-ratio norms) set this; such a rule is not valid on a
    # fused flat shard that spans parameter boundaries, so
    # update_sharding's elementwise-only guard refuses them
    _per_tensor_norms = False

    def _use_coupled_wd(self, p) -> bool:
        """L2Decay folds into the gradient (decoupled optimizers override)."""
        return self._weight_decay is not None and not self._decoupled_wd

    def clear_grad(self, set_to_zero: bool = False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # ---------------------------------------------------------- functional
    def _mp_applies(self, p) -> bool:
        return bool(self._multi_precision) and \
            jnp.issubdtype(p.dtype, jnp.floating) and p.dtype.itemsize == 2

    def _make_slots(self, p):
        """Slots for one param; multi_precision adds an fp32 master copy and
        keeps the moment buffers fp32 (reference master-weight contract —
        the low-precision param is a cast of the fp32 master)."""
        if self._mp_applies(p):
            m = p.astype(jnp.float32)
            slots = self._init_slots(m)
            slots["master"] = m
            return slots
        return self._init_slots(p)

    def init_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Build the optimizer-state pytree for a named param pytree."""
        state = {
            "step": jnp.zeros([], jnp.int32),
            "slots": jax.tree_util.tree_map(lambda p: self._make_slots(p), params,
                                            is_leaf=lambda x: hasattr(x, "shape")),
        }
        return state

    def update(self, grads: Dict[str, Any], state: Dict[str, Any],
               params: Dict[str, Any], lr=None):
        """Pure functional update: returns (new_params, new_state)."""
        lr = jnp.asarray(self.get_lr() if lr is None else lr, jnp.float32)
        if self._grad_clip is not None:
            grads = self._grad_clip._clip_pytree(grads)
        step = state["step"] + 1

        def upd(p, g, slots, pname):
            if g is None:
                return p, slots
            master = slots.get("master") if isinstance(slots, dict) else None
            tgt = master if master is not None else p
            g = g.astype(tgt.dtype) if g.dtype != tgt.dtype else g
            if self._weight_decay is not None and self._use_coupled_wd(object()):
                g = g + self._weight_decay.grad_term(tgt).astype(g.dtype)
            extra = {"param_name": pname} if self._wants_param_name else {}
            if master is not None:
                inner = {k: v for k, v in slots.items() if k != "master"}
                new_master, new_inner = self._rule(master, g, inner, lr,
                                                   step=step, **extra)
                new_inner["master"] = new_master
                return new_master.astype(p.dtype), new_inner
            return self._rule(p, g, slots, lr, step=step, **extra)

        flat_kp, treedef = jax.tree_util.tree_flatten_with_path(params)
        flat_p = [v for _, v in flat_kp]
        names = ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                          for k in path) for path, _ in flat_kp]
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        new_p, new_s = [], []
        for p, g, s, nm in zip(flat_p, flat_g, flat_s, names):
            np_, ns_ = upd(p, g, s, nm)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                {"step": step, "slots": jax.tree_util.tree_unflatten(treedef, new_s)})

    # -------------------------------------------------------------- state io
    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {"__step__": self._step_count}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                slots = self._accum.get(id(p))
                if slots:
                    pname = p.name or f"param_{i}"
                    for sname, val in slots.items():
                        sd[f"{pname}.{sname}"] = Tensor(val) if hasattr(val, "shape") \
                            else val
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]):
        self._step_count = int(state_dict.get("__step__", 0))
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                pname = p.name or f"param_{i}"
                slots = self._init_slots(p._data)
                found = False
                for sname in list(slots):
                    key = f"{pname}.{sname}"
                    if key in state_dict:
                        val = state_dict[key]
                        slots[sname] = jnp.asarray(
                            val.numpy() if hasattr(val, "numpy") else val)
                        found = True
                if found:
                    self._accum[id(p)] = slots
        from .lr import LRScheduler
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    # ------------------------------------------------------------ subclass
    def _init_slots(self, p) -> Dict[str, Any]:
        return {}

    def _rule(self, p, g, slots, lr, step=None):
        raise NotImplementedError
