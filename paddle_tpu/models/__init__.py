from .gpt import (GPT_CONFIGS, GPTConfig, GPTForPretraining, GPTModel,  # noqa: F401
                  gpt_preset, make_gpt_train_step)
