from .gpt import (GPT_CONFIGS, GPTConfig, GPTForPretraining, GPTModel,  # noqa: F401
                  gpt_preset, make_gpt_train_step)
from .bert import (BERT_CONFIGS, BertConfig, BertModel, bert_preset,  # noqa: F401
                   make_bert_train_step)
from .ernie_moe import (ErnieMoeConfig, ErnieMoeModel,  # noqa: F401
                        make_ernie_moe_train_step)
