"""Checkpoint conversion from torch/HuggingFace models.

≙ the reference ecosystem's weight converters (paddlenlp's
convert_*_checkpoint utilities; reference hapi models load torchvision-layout
state dicts the same way).  The converter doubles as the framework's
strongest correctness oracle: a torch GPT-2 and this GPT must produce the
same logits from the same weights (tests/test_convert.py).

Layout notes (HF GPT-2 → models/gpt.py):
- HF ``Conv1D`` stores (in, out) — the same orientation as our ``h @ W``
  matmuls, so attention/MLP weights transfer WITHOUT transposition.
- Per-layer tensors stack into the scan layout: ``blocks_*`` with a leading
  num_layers dim.
- ``lm_head`` is tied to ``wte`` in both (tie_word_embeddings).
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Tuple

import numpy as np


def _numpy_sd(hf_model, prefix: str) -> Tuple[Dict[str, Any], str]:
    """state_dict → numpy fp32, plus the detected submodule prefix (HF task
    heads wrap the backbone under e.g. 'transformer.'/'bert.'/'resnet.')."""
    sd = {k: v.detach().cpu().numpy().astype(np.float32)
          for k, v in hf_model.state_dict().items()}
    pre = prefix if any(k.startswith(prefix) for k in sd) else ""
    return sd, pre


def gpt2_params_from_torch(hf_model) -> Dict[str, Any]:
    """Convert a ``transformers.GPT2LMHeadModel`` (or GPT2Model) state dict
    into this framework's GPT param dict (stacked-scan layout, numpy fp32).

    Returns a dict loadable as ``params`` by ``GPTModel``'s pure functions;
    build the matching ``GPTConfig`` from ``hf_model.config`` via
    ``gpt2_config_from_torch``.
    """
    sd, pre = _numpy_sd(hf_model, "transformer.")
    L = max(int(k.split(".")[1 + (1 if pre else 0)])
            for k in sd if f"{pre}h." in k) + 1

    def layer(i, name):
        return sd[f"{pre}h.{i}.{name}"]

    def stack(name):
        return np.stack([layer(i, name) for i in range(L)])

    params = {
        "wte": sd[f"{pre}wte.weight"],
        "wpe": sd[f"{pre}wpe.weight"],
        "lnf_w": sd[f"{pre}ln_f.weight"],
        "lnf_b": sd[f"{pre}ln_f.bias"],
        "blocks_ln1_w": stack("ln_1.weight"),
        "blocks_ln1_b": stack("ln_1.bias"),
        "blocks_qkv_w": stack("attn.c_attn.weight"),   # Conv1D: (H, 3H) as-is
        "blocks_qkv_b": stack("attn.c_attn.bias"),
        "blocks_proj_w": stack("attn.c_proj.weight"),
        "blocks_proj_b": stack("attn.c_proj.bias"),
        "blocks_ln2_w": stack("ln_2.weight"),
        "blocks_ln2_b": stack("ln_2.bias"),
        "blocks_fc1_w": stack("mlp.c_fc.weight"),
        "blocks_fc1_b": stack("mlp.c_fc.bias"),
        "blocks_fc2_w": stack("mlp.c_proj.weight"),
        "blocks_fc2_b": stack("mlp.c_proj.bias"),
    }
    return params


def bert_params_from_torch(hf_model) -> Dict[str, Any]:
    """Convert a ``transformers.BertModel`` state dict into this framework's
    BERT param dict.  torch ``nn.Linear`` stores (out, in) — every dense
    weight transposes into our ``h @ W`` orientation; Q/K/V concatenate into
    the fused qkv projection."""
    sd, pre = _numpy_sd(hf_model, "bert.")
    L = max(int(k.split(".")[2 + (1 if pre else 0)])
            for k in sd if f"{pre}encoder.layer." in k) + 1

    def lw(i, name):  # layer tensor
        return sd[f"{pre}encoder.layer.{i}.{name}"]

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    def qkv_w(i):
        return np.concatenate(
            [lw(i, f"attention.self.{n}.weight").T for n in ("query", "key",
                                                             "value")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [lw(i, f"attention.self.{n}.bias") for n in ("query", "key",
                                                         "value")])

    emb = f"{pre}embeddings."
    params = {
        "word_emb": sd[emb + "word_embeddings.weight"],
        "pos_emb": sd[emb + "position_embeddings.weight"],
        "type_emb": sd[emb + "token_type_embeddings.weight"],
        "emb_ln_w": sd[emb + "LayerNorm.weight"],
        "emb_ln_b": sd[emb + "LayerNorm.bias"],
        "blocks_qkv_w": stack(qkv_w),
        "blocks_qkv_b": stack(qkv_b),
        "blocks_proj_w": stack(lambda i: lw(i, "attention.output.dense.weight").T),
        "blocks_proj_b": stack(lambda i: lw(i, "attention.output.dense.bias")),
        "blocks_ln1_w": stack(lambda i: lw(i, "attention.output.LayerNorm.weight")),
        "blocks_ln1_b": stack(lambda i: lw(i, "attention.output.LayerNorm.bias")),
        "blocks_fc1_w": stack(lambda i: lw(i, "intermediate.dense.weight").T),
        "blocks_fc1_b": stack(lambda i: lw(i, "intermediate.dense.bias")),
        "blocks_fc2_w": stack(lambda i: lw(i, "output.dense.weight").T),
        "blocks_fc2_b": stack(lambda i: lw(i, "output.dense.bias")),
        "blocks_ln2_w": stack(lambda i: lw(i, "output.LayerNorm.weight")),
        "blocks_ln2_b": stack(lambda i: lw(i, "output.LayerNorm.bias")),
    }
    # pooler is absent on add_pooling_layer=False backbones (BertForMaskedLM)
    if f"{pre}pooler.dense.weight" in sd:
        params["pooler_w"] = sd[f"{pre}pooler.dense.weight"].T
        params["pooler_b"] = sd[f"{pre}pooler.dense.bias"]
    # MLM head (BertForMaskedLM / BertForPreTraining: cls.predictions.*)
    if "cls.predictions.transform.dense.weight" in sd:
        params["mlm_dense_w"] = sd["cls.predictions.transform.dense.weight"].T
        params["mlm_dense_b"] = sd["cls.predictions.transform.dense.bias"]
        params["mlm_ln_w"] = sd["cls.predictions.transform.LayerNorm.weight"]
        params["mlm_ln_b"] = sd["cls.predictions.transform.LayerNorm.bias"]
        params["mlm_bias"] = sd["cls.predictions.bias"]
    # NSP head (BertForPreTraining: cls.seq_relationship)
    if "cls.seq_relationship.weight" in sd:
        params["nsp_w"] = sd["cls.seq_relationship.weight"].T
        params["nsp_b"] = sd["cls.seq_relationship.bias"]
    return params


def bert_config_from_torch(hf_config, **overrides):
    """Build the matching BertConfig from a ``transformers.BertConfig``."""
    from .bert import BertConfig

    kw = dict(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_hidden_layers=hf_config.num_hidden_layers,
        num_attention_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        hidden_act=_map_act(hf_config.hidden_act),
    )
    kw.update(overrides)
    return BertConfig(**kw)


def gpt2_config_from_torch(hf_config, **overrides):
    """Build the matching GPTConfig from a ``transformers.GPT2Config``."""
    from .gpt import GPTConfig

    kw = dict(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_attention_heads=hf_config.n_head,
        intermediate_size=getattr(hf_config, "n_inner", None) or
        4 * hf_config.n_embd,
        max_position_embeddings=hf_config.n_positions,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        tie_word_embeddings=True,
        hidden_act=_map_act(hf_config.activation_function),
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def resnet_state_dict_from_torch(hf_model) -> Dict[str, Any]:
    """Convert a ``transformers.ResNetModel`` / ``ResNetForImageClassification``
    state dict into this framework's torchvision-layout ResNet state dict
    (vision/models/resnet.py) — conv weights stay OIHW; BN running stats map
    to the paddle ``_mean``/``_variance`` slots; the classifier Linear
    transposes to our (in, out) orientation.

    Requires ``downsample_in_bottleneck=False`` on the HF config (the
    torchvision v1.5 stride placement this framework implements).
    """
    cfg = hf_model.config
    if getattr(cfg, "downsample_in_bottleneck", False):
        raise ValueError("downsample_in_bottleneck=True puts the stride in "
                         "the 1x1 conv; this framework implements the "
                         "torchvision v1.5 layout (stride in the 3x3)")
    if getattr(cfg, "downsample_in_first_stage", False):
        raise ValueError("downsample_in_first_stage=True strides stage 0; "
                         "this framework's layer1 is stride 1 (torchvision "
                         "layout) — the weights would load but compute "
                         "wrong logits")
    if getattr(cfg, "hidden_act", "relu") != "relu":
        raise ValueError(f"hidden_act={cfg.hidden_act!r} unsupported: the "
                         f"framework's ResNet blocks use ReLU (torchvision "
                         f"semantics)")
    sd, pre = _numpy_sd(hf_model, "resnet.")

    def bn(dst, src):
        return {f"{dst}.weight": sd[f"{src}.weight"],
                f"{dst}.bias": sd[f"{src}.bias"],
                f"{dst}._mean": sd[f"{src}.running_mean"],
                f"{dst}._variance": sd[f"{src}.running_var"]}

    out: Dict[str, Any] = {
        "conv1.weight": sd[f"{pre}embedder.embedder.convolution.weight"]}
    out.update(bn("bn1", f"{pre}embedder.embedder.normalization"))

    n_stages = len(hf_model.config.depths)
    for s in range(n_stages):
        for j in range(hf_model.config.depths[s]):
            hfp = f"{pre}encoder.stages.{s}.layers.{j}"
            ours = f"layer{s + 1}.{j}"
            i = 0
            while f"{hfp}.layer.{i}.convolution.weight" in sd:
                out[f"{ours}.conv{i + 1}.weight"] = \
                    sd[f"{hfp}.layer.{i}.convolution.weight"]
                out.update(bn(f"{ours}.bn{i + 1}",
                              f"{hfp}.layer.{i}.normalization"))
                i += 1
            if f"{hfp}.shortcut.convolution.weight" in sd:
                out[f"{ours}.downsample.0.weight"] = \
                    sd[f"{hfp}.shortcut.convolution.weight"]
                out.update(bn(f"{ours}.downsample.1",
                              f"{hfp}.shortcut.normalization"))
    if "classifier.1.weight" in sd:
        out["fc.weight"] = sd["classifier.1.weight"].T
        out["fc.bias"] = sd["classifier.1.bias"]
    else:
        warnings.warn(
            "converted a headless ResNetModel backbone: no classifier in the "
            "checkpoint, so fc.weight/fc.bias are NOT in the returned dict — "
            "the target model's head keeps its current (random) init",
            stacklevel=2)
    return out


def _map_act(name: str) -> str:
    """HF activation names → this framework's knob (exact vs tanh gelu)."""
    mapping = {"gelu": "gelu", "gelu_new": "gelu_approx",
               "gelu_pytorch_tanh": "gelu_approx", "gelu_approx": "gelu_approx"}
    if name not in mapping:
        raise ValueError(f"unsupported activation {name!r}; supported: "
                         f"{sorted(mapping)}")
    return mapping[name]
