"""ERNIE-style MoE transformer (reference capability: "ERNIE MoE alltoall"
config in BASELINE.json; EP transport ≙ global_scatter/global_gather,
distributed/utils.py:57,179).

Decoder-only transformer where every block's FFN is a top-k routed mixture of
experts.  TPU-first: blocks stacked for ``lax.scan`` (expert weights get an
extra leading layer dim: (L, E, H, I)); expert parallelism is a sharding
constraint on the dispatched (E, C, H) tensor — GSPMD emits the token
all_to_all over the expert mesh axis.  Aux (load-balance) losses are summed
over layers via the scan carry.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.layer.base import Layer
from ._decode import CausalDecoderMixin
from ..ops.attention import flash_attention
from ..ops.moe import moe_ffn, moe_ffn_gather, moe_ffn_indices


class ErnieMoeConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, num_experts=8, top_k=2,
                 expert_hidden_size=None, capacity_factor=1.25,
                 max_position_embeddings=1024, initializer_range=0.02,
                 layer_norm_epsilon=1e-5, compute_dtype="bfloat16",
                 aux_loss_weight=0.01, expert_axis="data", scan_unroll=1,
                 index_dispatch=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_attention_heads = num_attention_heads
        self.num_experts = num_experts
        self.top_k = top_k
        self.expert_hidden_size = expert_hidden_size or 4 * hidden_size
        self.capacity_factor = capacity_factor
        self.max_position_embeddings = max_position_embeddings
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.compute_dtype = compute_dtype
        self.aux_loss_weight = aux_loss_weight
        self.expert_axis = expert_axis
        self.scan_unroll = scan_unroll
        self.index_dispatch = index_dispatch


class ErnieMoeModel(CausalDecoderMixin, Layer):
    """Causal LM with MoE FFNs in every block."""

    def __init__(self, config: ErnieMoeConfig):
        super().__init__()
        self.config = c = config
        L, H, V, E = c.num_layers, c.hidden_size, c.vocab_size, c.num_experts
        I = c.expert_hidden_size
        std = c.initializer_range

        def normal(shape, s=std):
            from ..nn.initializer import Normal
            return Normal(0.0, s)(shape, "float32")

        def param(name, data, mapping=None):
            p = Parameter(data, name=name)
            if mapping:
                p._dims_mapping = mapping
            self.add_parameter(name.replace(".", "_"), p)
            return p

        zeros = lambda s: jnp.zeros(s, jnp.float32)
        ones = lambda s: jnp.ones(s, jnp.float32)
        self.wte = param("wte", normal([V, H]), {0: "model"})
        self.wpe = param("wpe", normal([c.max_position_embeddings, H]))
        self.blocks_ln1_w = param("blocks.ln1_w", ones([L, H]))
        self.blocks_ln1_b = param("blocks.ln1_b", zeros([L, H]))
        self.blocks_qkv_w = param("blocks.qkv_w", normal([L, H, 3 * H]), {2: "model"})
        self.blocks_qkv_b = param("blocks.qkv_b", zeros([L, 3 * H]), {1: "model"})
        self.blocks_proj_w = param("blocks.proj_w",
                                   normal([L, H, H], std / math.sqrt(2 * L)),
                                   {1: "model"})
        self.blocks_proj_b = param("blocks.proj_b", zeros([L, H]))
        self.blocks_ln2_w = param("blocks.ln2_w", ones([L, H]))
        self.blocks_ln2_b = param("blocks.ln2_b", zeros([L, H]))
        # MoE FFN: gate + stacked experts, leading (L, E) dims
        self.blocks_gate_w = param("blocks.gate_w", normal([L, H, E]))
        self.blocks_expert_w1 = param("blocks.expert_w1", normal([L, E, H, I]),
                                      {1: c.expert_axis})
        self.blocks_expert_b1 = param("blocks.expert_b1", zeros([L, E, I]),
                                      {1: c.expert_axis})
        self.blocks_expert_w2 = param("blocks.expert_w2",
                                      normal([L, E, I, H], std / math.sqrt(2 * L)),
                                      {1: c.expert_axis})
        self.blocks_expert_b2 = param("blocks.expert_b2", zeros([L, E, H]),
                                      {1: c.expert_axis})
        self.lnf_w = param("lnf_w", ones([H]))
        self.lnf_b = param("lnf_b", zeros([H]))

    @staticmethod
    def stacked_param_names():
        return [f"blocks_{n}" for n in
                ("ln1_w", "ln1_b", "qkv_w", "qkv_b", "proj_w", "proj_b",
                 "ln2_w", "ln2_b", "gate_w", "expert_w1", "expert_b1",
                 "expert_w2", "expert_b2")]

    # -------------------------------------------------------- pure functions
    def embed_fn(self, params, input_ids, key=None):
        c = self.config
        pos = jnp.arange(input_ids.shape[-1])
        h = jnp.take(params["wte"], input_ids, axis=0) + params["wpe"][pos]
        return h.astype(jnp.dtype(c.compute_dtype))

    def _block_ln(self, x, w, b, dt):
        x32 = x.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        return ((x32 - m) * jax.lax.rsqrt(v + self.config.layer_norm_epsilon)
                * w + b).astype(dt)

    def _block_qkv(self, sl, h):
        """pre-LN + fused QKV; returns q, k, v as (B, L, nh, hd)."""
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        nh = c.num_attention_heads
        hd = H // nh
        a_in = self._block_ln(h, sl["blocks_ln1_w"], sl["blocks_ln1_b"], dt)
        qkv = a_in @ sl["blocks_qkv_w"].astype(dt) + sl["blocks_qkv_b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        return (q.reshape(B, Lq, nh, hd), k.reshape(B, Lq, nh, hd),
                v.reshape(B, Lq, nh, hd))

    def _attn_residual(self, sl, h, att):
        dt = h.dtype
        B, Lq, H = h.shape
        att = att.reshape(B, Lq, H)
        return h + att @ sl["blocks_proj_w"].astype(dt) \
            + sl["blocks_proj_b"].astype(dt)

    def _moe_residual(self, sl, h, mesh=None, capacity_factor=None):
        """ln2 + routed FFN + residual.  capacity_factor=None → training
        config; a float overrides (generation passes the no-drop value)."""
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        m_in = self._block_ln(h, sl["blocks_ln2_w"], sl["blocks_ln2_b"], dt)
        tokens = m_in.reshape(B * Lq, H)
        # index (gather/scatter) dispatch by default — the einsum dispatch's
        # (T, E, C) masks cost ~2x the expert FLOPs at bench shapes
        ffn = moe_ffn_indices if getattr(c, "index_dispatch", True) else moe_ffn
        out, aux = ffn(tokens, sl["blocks_gate_w"], sl["blocks_expert_w1"],
                       sl["blocks_expert_b1"], sl["blocks_expert_w2"],
                       sl["blocks_expert_b2"], k=c.top_k,
                       capacity_factor=(c.capacity_factor
                                        if capacity_factor is None
                                        else capacity_factor),
                       mesh=mesh, expert_axis=c.expert_axis)
        return h + out.reshape(B, Lq, H), aux

    def block_fn(self, sl: Dict[str, Any], h, mesh=None):
        """One block; returns (h, aux_loss)."""
        q, k, v = self._block_qkv(sl, h)
        att = flash_attention(q, k, v, causal=True)
        h = self._attn_residual(sl, h, att)
        return self._moe_residual(sl, h, mesh=mesh)

    def scan_blocks(self, params, h, mesh=None, remat=True):
        stacked = {k: params[k] for k in self.stacked_param_names()}
        fn = self.block_fn
        if remat:
            fn = jax.checkpoint(lambda sl, hh: self.block_fn(sl, hh, mesh))
        else:
            fn = lambda sl, hh: self.block_fn(sl, hh, mesh)

        def body(carry, sl):
            hh, aux_sum = carry
            hh, aux = fn(sl, hh)
            return (hh, aux_sum + aux), None

        from ._scan import resolve_scan_unroll
        (out, aux_sum), _ = jax.lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                                         stacked,
                                         unroll=resolve_scan_unroll(self.config))
        return out, aux_sum

    def _head_logits(self, params, h, dtype=None):
        c = self.config
        x32 = h.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        hn = (x32 - m) * jax.lax.rsqrt(v + c.layer_norm_epsilon) * params["lnf_w"] \
            + params["lnf_b"]
        dt = jnp.dtype(c.compute_dtype) if dtype is None else dtype
        return hn.astype(dt) @ params["wte"].astype(dt).T

    def head_loss_fn(self, params, h, labels, aux_sum=0.0):
        # fused CE — no fp32 (B, L, V) log-prob tensor (ops/loss.py)
        from ..ops.loss import softmax_cross_entropy_mean
        nll = softmax_cross_entropy_mean(self._head_logits(params, h), labels)
        return nll + self.config.aux_loss_weight * aux_sum

    # ------------------------------------------------------------- nn.Layer
    def forward(self, input_ids, labels=None):
        raw = getattr(input_ids, "_data", input_ids)
        params = {n: p._data for n, p in self.named_parameters()}
        h = self.embed_fn(params, raw)
        h, aux = self.scan_blocks(params, h, remat=False)
        if labels is None:
            logits = self._head_logits(params, h, dtype=jnp.float32)
            return Tensor(logits) if isinstance(input_ids, Tensor) else logits
        raw_labels = getattr(labels, "_data", labels)
        loss = self.head_loss_fn(params, h, raw_labels, aux)
        return Tensor(loss) if isinstance(input_ids, Tensor) else loss

    # ------------------------------------------------- KV-cache generation
    # Same static-cache single-scan design as models/gpt.py, with one MoE
    # twist: capacity-based token dropping is CONTEXT-dependent, so an
    # incremental decode only reproduces the full forward if nothing drops.
    # Generation therefore routes with a no-drop capacity (cf = E/k ⇒
    # C >= T always) in both prefill and decode — which is also the right
    # serving behavior (dropping a live request's FFN output is not an
    # option at inference).

    def _nodrop_cf(self) -> float:
        c = self.config
        return float(c.num_experts) / float(c.top_k)

    def _moe_residual_gather(self, sl, h):
        """ln2 + capacity-free gather-dispatch FFN + residual — the decode
        hot path: O(k·T) expert FLOPs, no (E, C, H) buffer (ops/moe.py:
        moe_ffn_gather; equal to the no-drop indices path by test)."""
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        m_in = self._block_ln(h, sl["blocks_ln2_w"], sl["blocks_ln2_b"], dt)
        out = moe_ffn_gather(m_in.reshape(B * Lq, H), sl["blocks_gate_w"],
                             sl["blocks_expert_w1"], sl["blocks_expert_b1"],
                             sl["blocks_expert_w2"], sl["blocks_expert_b2"],
                             k=c.top_k)
        return h + out.reshape(B, Lq, H)

    def _block_decode(self, sl, h, ck, cv, t, pad_lens=None):
        """One block for one new token at position t (h (B,1,H); ck/cv
        (B, max_len, nh, hd))."""
        from ._decode import cached_attention, dequantize_cache, write_cache
        q, k, v = self._block_qkv(sl, h)
        ck = write_cache(ck, k, t)
        cv = write_cache(cv, v, t)
        att = cached_attention(q, dequantize_cache(ck, q.dtype),
                               dequantize_cache(cv, q.dtype), t,
                               pad_lens=pad_lens)
        h = self._attn_residual(sl, h, att)
        return self._moe_residual_gather(sl, h), ck, cv

    def prefill(self, params, input_ids, max_len: int, pad_lens=None):
        """Prompt pass with no-drop routing; returns (h, (ck, cv)) with
        caches filled at [0, P).  Uses the buffered no-drop indices dispatch
        (cf = E/k): at prefill T = B·P is large, so gathering (T, k, H, I)
        weight slices would cost more than the padded buffer does.  With
        ``pad_lens`` (left-padded prompts), pad keys get a finite -1e30 mask
        and positions shift per row (see GPT.prefill)."""
        c = self.config
        B, P = input_ids.shape
        if pad_lens is None:
            h, key_mask = self.embed_fn(params, input_ids), None
        else:
            h = self._prefill_embed(params, input_ids, pad_lens)
            key_mask = self._prefill_key_mask(P, pad_lens)
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, sl):
            q, k, v = self._block_qkv(sl, carry)
            att = flash_attention(q, k, v, causal=True, key_mask=key_mask)
            hh = self._attn_residual(sl, carry, att)
            hh, _ = self._moe_residual(sl, hh,
                                       capacity_factor=self._nodrop_cf())
            return hh, (k, v)

        h, (ks, vs) = jax.lax.scan(body, h, stacked)
        pad = [(0, 0), (0, 0), (0, max_len - P), (0, 0), (0, 0)]
        cdt = jnp.dtype(c.compute_dtype)
        return h, (jnp.pad(ks.astype(cdt), pad), jnp.pad(vs.astype(cdt), pad))

    def _block_decode_ragged(self, sl, h, pck, pcv, table, row_seq,
                             row_pos, pad_lens):
        """One block for a flattened ragged pack (the mixed serving step;
        see GPTModel._block_decode_ragged): scatter each row's k/v to its
        table-mapped pool position BEFORE attention, then the gather-
        dispatch MoE FFN — the no-drop decode hot path."""
        from ._decode import ragged_attention, ragged_write
        q, k, v = self._block_qkv(sl, h)               # (1, T, nh, hd)
        pck = ragged_write(pck, k[0], table, row_seq, row_pos)
        pcv = ragged_write(pcv, v[0], table, row_seq, row_pos)
        att = ragged_attention(q[0], pck, pcv, table, row_seq, row_pos,
                               pad_lens)
        h = self._attn_residual(sl, h, att[None])
        return self._moe_residual_gather(sl, h), pck, pcv

    def decode_ragged(self, params, h, pools, table, row_seq, row_pos,
                      pad_lens):
        """All blocks for one mixed ragged step (the ragged serving
        engine's fused prefill+decode+verify tick) — the MoE counterpart
        of GPTModel.decode_ragged, so MoE targets ride the ragged engine
        (speculative verification included) through the same mixin
        contract."""
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, xs):
            sl, pck, pcv = xs
            out, pck, pcv = self._block_decode_ragged(
                sl, carry, pck, pcv, table, row_seq, row_pos, pad_lens)
            return out, (pck, pcv)

        h, (cks, cvs) = jax.lax.scan(body, h, (stacked, pools[0], pools[1]))
        return h, (cks, cvs)

    def decode_step(self, params, h, caches, t, pad_lens=None):
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, xs):
            sl, ck, cv = xs
            out, ck, cv = self._block_decode(sl, carry, ck, cv, t,
                                             pad_lens=pad_lens)
            return out, (ck, cv)

        h, (cks, cvs) = jax.lax.scan(body, h, (stacked, caches[0], caches[1]))
        return h, (cks, cvs)

    def decode_logits(self, params, h):
        """fp32 logits for the shared decode loops (CausalDecoderMixin)."""
        return self._head_logits(params, h, dtype=jnp.float32)


def make_ernie_moe_train_step(model: ErnieMoeModel, optimizer, hcg,
                              remat: bool = True, donate: bool = True):
    """Expert-parallel (+dp/mp) train step over the hybrid mesh."""
    from ..distributed.spmd import make_gspmd_step_from_loss

    mesh = hcg.mesh
    params0 = {n: p._data for n, p in model.named_parameters()}

    def loss_of(params, input_ids, labels):
        h = model.embed_fn(params, input_ids)
        h, aux = model.scan_blocks(params, h, mesh=mesh, remat=remat)
        return model.head_loss_fn(params, h, labels, aux)

    return make_gspmd_step_from_loss(loss_of, params0, optimizer, mesh,
                                     layer=model, donate=donate)


def make_sharded_ernie_moe_train_step(cfg: ErnieMoeConfig, optimizer, hcg,
                                      zero_stage: int = 0, seed: int = 0,
                                      remat: bool = True, donate: bool = True):
    """ERNIE-MoE step with mesh-direct sharded init (see models/gpt.py
    make_sharded_gpt_train_step — sharding SPECS only)."""
    from ..core import rng as _rng
    from ..distributed.spmd import make_gspmd_sharded_init_step

    mesh = hcg.mesh
    holder = {}

    def build(key):
        with _rng.rng_scope(key):
            m = ErnieMoeModel(cfg)
        holder.setdefault("model", m)
        return {n: p._data for n, p in m.named_parameters()}

    jax.eval_shape(build, jax.random.key(seed))
    meta = holder["model"]

    def loss_of(params, input_ids, labels):
        h = meta.embed_fn(params, input_ids)
        h, aux = meta.scan_blocks(params, h, mesh=mesh, remat=remat)
        return meta.head_loss_fn(params, h, labels, aux)

    return make_gspmd_sharded_init_step(loss_of, build, optimizer, mesh,
                                        meta, zero_stage=zero_stage,
                                        donate=donate, seed=seed)
