"""GPT model family — the flagship for hybrid-parallel training.

Reference capability: the fleet hybrid-parallel GPT tests
(hybrid_parallel_pp_transformer.py, GPT-3 configs in BASELINE.json).

TPU-first design decisions:
- **Stacked blocks**: all L transformer blocks live in ONE pytree with a
  leading layer dim, consumed by ``lax.scan`` — one compiled block program
  regardless of depth (compile time O(1) in L), and the leading dim is the
  natural pipeline-stage shard ("pipe") for the shard_map pipeline engine.
- **TP via dims_mapping**: qkv/fc1 are column-parallel (out dim on "model"),
  proj/fc2 row-parallel (in dim on "model") — GSPMD inserts the allreduces
  the reference's ColumnParallelLinear/RowParallelLinear issue explicitly.
- **Sequence parallel**: activations constrained to P("data", "sep", None)
  between blocks when a "sep" axis exists.
- **bf16 compute, fp32 params** by default; flash attention from
  paddle_tpu.ops (Pallas on TPU).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.tensor import Parameter, Tensor, apply
from ._decode import (CausalDecoderMixin, cached_attention,  # noqa: F401
                      dequantize_cache, make_token_sampler, quantize_kv,
                      ragged_attention, ragged_write,
                      validate_sampler_args, write_cache)
from ..nn.layer.base import Layer
from ..ops.attention import flash_attention


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0, initializer_range=0.02,
                 layer_norm_epsilon=1e-5, compute_dtype="bfloat16",
                 use_flash_attention=True, tie_word_embeddings=True,
                 sequence_parallel=None, scan_unroll=1,
                 hidden_act="gelu_approx", kv_cache_dtype=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.compute_dtype = compute_dtype
        self.use_flash_attention = use_flash_attention
        self.tie_word_embeddings = tie_word_embeddings
        self.scan_unroll = scan_unroll  # layers per scan step (see scan_blocks)
        # GPT-2's canonical activation is the tanh approximation ("gelu_new")
        # — hence the approx default; "gelu" selects the exact erf form
        if hidden_act not in ("gelu", "gelu_approx"):
            raise ValueError(f"hidden_act must be 'gelu' or 'gelu_approx', "
                             f"got {hidden_act!r}")
        self.hidden_act = hidden_act
        # None → KV cache stored in compute_dtype; "int8" → per-(position,
        # head) symmetric-quantized cache (half the decode HBM traffic of
        # bf16; serving accuracy tradeoff, see models/_decode.py)
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype must be None or 'int8', "
                             f"got {kv_cache_dtype!r}")
        self.kv_cache_dtype = kv_cache_dtype
        # None → GSPMD decides (sequence gathered for attention);
        # "ring"/"ulysses" → explicit context parallelism over the "sep" axis
        if sequence_parallel not in (None, "ring", "ulysses"):
            raise ValueError(f"sequence_parallel must be None, 'ring' or "
                             f"'ulysses', got {sequence_parallel!r}")
        self.sequence_parallel = sequence_parallel


# canonical sizes (GPT-3 paper / fleet configs)
GPT_CONFIGS = {
    "gpt2-small": dict(hidden_size=768, num_layers=12, num_attention_heads=12),
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_attention_heads=16),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_attention_heads=20),
    "gpt3-1.3B": dict(hidden_size=2048, num_layers=24, num_attention_heads=16),
    "gpt3-2.7B": dict(hidden_size=2560, num_layers=32, num_attention_heads=32),
    "gpt3-6.7B": dict(hidden_size=4096, num_layers=32, num_attention_heads=32),
    "gpt3-13B": dict(hidden_size=5120, num_layers=40, num_attention_heads=40),
}


class GPTModel(CausalDecoderMixin, Layer):
    """Decoder-only transformer with stacked block parameters."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = c = config
        L, H, V = c.num_layers, c.hidden_size, c.vocab_size
        I = c.intermediate_size
        std = c.initializer_range

        def normal(shape, s=std):
            from ..nn.initializer import Normal
            return Normal(0.0, s)(shape, "float32")

        def zeros(shape):
            return jnp.zeros(shape, jnp.float32)

        def ones(shape):
            return jnp.ones(shape, jnp.float32)

        def param(name, data, mapping=None):
            p = Parameter(data, name=name)
            if mapping:
                p._dims_mapping = mapping
            self.add_parameter(name.replace(".", "_"), p)
            return p

        # embeddings (vocab-parallel like VocabParallelEmbedding)
        self.wte = param("wte", normal([V, H]), {0: "model"})
        self.wpe = param("wpe", normal([c.max_position_embeddings, H]))
        # stacked blocks — column-parallel qkv/fc1, row-parallel proj/fc2
        # (reference: fused_attention_op.cu QKV fused gemm; fleet mp_layers)
        self.blocks_ln1_w = param("blocks.ln1_w", ones([L, H]))
        self.blocks_ln1_b = param("blocks.ln1_b", zeros([L, H]))
        self.blocks_qkv_w = param("blocks.qkv_w", normal([L, H, 3 * H]), {2: "model"})
        self.blocks_qkv_b = param("blocks.qkv_b", zeros([L, 3 * H]), {1: "model"})
        self.blocks_proj_w = param("blocks.proj_w",
                                   normal([L, H, H], std / math.sqrt(2 * L)),
                                   {1: "model"})
        self.blocks_proj_b = param("blocks.proj_b", zeros([L, H]))
        self.blocks_ln2_w = param("blocks.ln2_w", ones([L, H]))
        self.blocks_ln2_b = param("blocks.ln2_b", zeros([L, H]))
        self.blocks_fc1_w = param("blocks.fc1_w", normal([L, H, I]), {2: "model"})
        self.blocks_fc1_b = param("blocks.fc1_b", zeros([L, I]), {1: "model"})
        self.blocks_fc2_w = param("blocks.fc2_w",
                                  normal([L, I, H], std / math.sqrt(2 * L)),
                                  {1: "model"})
        self.blocks_fc2_b = param("blocks.fc2_b", zeros([L, H]))
        self.lnf_w = param("lnf_w", ones([H]))
        self.lnf_b = param("lnf_b", zeros([H]))
        if not c.tie_word_embeddings:
            self.lm_head = param("lm_head", normal([H, V]), {1: "model"})

    # -------------------------------------------------------- pure functions
    @staticmethod
    def stacked_param_names():
        return [f"blocks_{n}" for n in ("ln1_w", "ln1_b", "qkv_w", "qkv_b",
                                        "proj_w", "proj_b", "ln2_w", "ln2_b",
                                        "fc1_w", "fc1_b", "fc2_w", "fc2_b")]

    def embed_fn(self, params: Dict[str, Any], input_ids, key=None):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        pos = jnp.arange(input_ids.shape[-1])
        h = jnp.take(params["wte"], input_ids, axis=0) + params["wpe"][pos]
        return h.astype(dt)

    def _block_ln(self, x, w, b, dt):
        x32 = x.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        return ((x32 - m) * jax.lax.rsqrt(v + self.config.layer_norm_epsilon)
                * w + b).astype(dt)

    def _block_qkv(self, sl, h):
        """pre-LN + QKV projection; returns q, k, v as (B, L, nh, hd)."""
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        nh = c.num_attention_heads
        hd = H // nh
        a_in = self._block_ln(h, sl["blocks_ln1_w"], sl["blocks_ln1_b"], dt)
        qkv = a_in @ sl["blocks_qkv_w"].astype(dt) + sl["blocks_qkv_b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        return (q.reshape(B, Lq, nh, hd), k.reshape(B, Lq, nh, hd),
                v.reshape(B, Lq, nh, hd))

    def _block_post_attn(self, sl, h, att):
        """attention output projection + residual + MLP half of the block."""
        dt = h.dtype
        B, Lq, H = h.shape
        att = att.reshape(B, Lq, H)
        h = h + att @ sl["blocks_proj_w"].astype(dt) + sl["blocks_proj_b"].astype(dt)
        m_in = self._block_ln(h, sl["blocks_ln2_w"], sl["blocks_ln2_b"], dt)
        ff = jax.nn.gelu(m_in @ sl["blocks_fc1_w"].astype(dt)
                         + sl["blocks_fc1_b"].astype(dt),
                         approximate=self.config.hidden_act == "gelu_approx")
        return h + ff @ sl["blocks_fc2_w"].astype(dt) + sl["blocks_fc2_b"].astype(dt)

    def block_fn(self, sl: Dict[str, Any], h, key=None, sp_mesh=None):
        """One transformer block given this layer's parameter slice.

        ``sp_mesh``: when set (by make_gpt_train_step on a mesh with sep>1)
        attention runs as explicit ring/Ulysses context parallelism over the
        "sep" axis instead of letting GSPMD gather the sequence."""
        c = self.config
        B, Lq, H = h.shape
        q, k, v = self._block_qkv(sl, h)
        sp_mode = getattr(c, "sequence_parallel", None)
        mesh = sp_mesh
        if sp_mode and mesh is not None and mesh.shape.get("sep", 1) > 1:
            if Lq % mesh.shape["sep"] != 0:
                # never fall back silently — gathered attention is exactly the
                # O(L) per-device memory blowup the user opted out of
                raise ValueError(
                    f"sequence_parallel={sp_mode!r} needs seq_len ({Lq}) "
                    f"divisible by the sep degree ({mesh.shape['sep']}); pad "
                    f"the sequence or change sep_degree")
            # context parallelism: activations stay sequence-sharded on "sep";
            # ring/Ulysses attention inside a partial-manual shard_map region
            # (only "sep" is manual — dp/mp stay under GSPMD)
            from ..distributed.sharding_rules import sep_activation_spec
            from ..distributed.spmd import shard_map
            from ..ops.ring_attention import sequence_parallel_attention
            att = shard_map(
                functools.partial(sequence_parallel_attention, axis_name="sep",
                                  causal=True, mode=sp_mode),
                mesh=mesh, in_specs=sep_activation_spec(),
                out_specs=sep_activation_spec(), axis_names={"sep"},
            )(q, k, v)
        else:
            att = flash_attention(q, k, v, causal=True)
        return self._block_post_attn(sl, h, att)

    def _head_logits(self, params: Dict[str, Any], h):
        c = self.config
        x32 = h.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        h = (x32 - m) * jax.lax.rsqrt(v + c.layer_norm_epsilon) * params["lnf_w"] \
            + params["lnf_b"]
        w = params.get("lm_head")
        if w is None:
            w = params["wte"].T
        dt = jnp.dtype(c.compute_dtype)
        return h.astype(dt) @ w.astype(dt)

    def head_fn(self, params: Dict[str, Any], h):
        return self._head_logits(params, h).astype(jnp.float32)

    def head_loss_fn(self, params: Dict[str, Any], h, labels):
        # fused CE on compute-dtype logits: never materializes the fp32
        # (B, L, V) log-prob tensor (ops/loss.py — ≙ the reference's fused
        # softmax_with_cross_entropy, operators/math/cross_entropy.cu)
        from ..ops.loss import softmax_cross_entropy_mean
        return softmax_cross_entropy_mean(self._head_logits(params, h), labels)

    def scan_blocks(self, params, h, key=None, remat=True, sp_mesh=None):
        """``remat``: False = save all activations; True = full per-block
        recompute (≙ RecomputeOptimizer, fluid/optimizer.py:5930); "dots" =
        selective policy that saves MXU (matmul) outputs and recomputes only
        elementwise interiors — near-full-speed backward at a fraction of the
        activation memory (the TPU-idiomatic default for large batches)."""
        stacked = {k: params[k] for k in self.stacked_param_names()}
        if remat:
            policy = None
            if remat == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            fn = jax.checkpoint(
                lambda sl, hh: self.block_fn(sl, hh, key, sp_mesh=sp_mesh),
                policy=policy)

            def body(carry, sl):
                return fn(sl, carry), None
        else:
            def body(carry, sl):
                return self.block_fn(sl, carry, key, sp_mesh=sp_mesh), None
        from ._scan import resolve_scan_unroll
        out, _ = jax.lax.scan(body, h, stacked,
                              unroll=resolve_scan_unroll(self.config))
        return out

    # ------------------------------------------------------------- nn.Layer
    def forward(self, input_ids, position_ids=None, attention_mask=None,
                use_cache=False, cache=None):
        raw = getattr(input_ids, "_data", input_ids)
        params = {n: p._data for n, p in self.named_parameters()}
        h = self.embed_fn(params, raw)
        h = self.scan_blocks(params, h, remat=False)
        logits = self.head_fn(params, h)
        return Tensor(logits) if isinstance(input_ids, Tensor) else logits

    # ------------------------------------------------- KV-cache generation
    # machinery shared via CausalDecoderMixin (models/_decode.py);
    # GPT provides the model-specific pieces: prefill, decode_step,
    # decode_logits.

    def decode_logits(self, params, h):
        """fp32 logits for the decode loops (mixin contract)."""
        return self.head_fn(params, h)

    def _block_decode(self, sl, h, ck, cv, t, pad_lens=None):
        """One block for ONE new token at position ``t``.

        h (B, 1, H); ck/cv (B, max_len, nh, hd) are this layer's caches.
        Returns (h_out, ck, cv) with the new k/v written at index t and
        attention taken over cache positions ≤ t (later slots hold zeros or
        stale values — and left-pad slots, when pad_lens is set — masked)."""
        q, k, v = self._block_qkv(sl, h)
        ck = write_cache(ck, k, t)
        cv = write_cache(cv, v, t)
        # int8 caches dequantize here; XLA fuses the convert*scale into the
        # attention einsum's operand read (no fp cache copy materializes)
        dt = q.dtype
        att = cached_attention(q, dequantize_cache(ck, dt),
                               dequantize_cache(cv, dt), t,
                               pad_lens=pad_lens)
        return self._block_post_attn(sl, h, att), ck, cv

    def prefill(self, params, input_ids, max_len: int, pad_lens=None):
        """Run the prompt through all blocks, returning the final hidden
        states (B, P, H) and caches filled at positions [0, P).  With
        ``pad_lens`` (left-padded prompts), embedding positions shift and
        pad keys are masked (mixin helpers — one canonical convention)."""
        c = self.config
        B, P = input_ids.shape
        if pad_lens is None:
            h, key_mask = self.embed_fn(params, input_ids), None
        else:
            h = self._prefill_embed(params, input_ids, pad_lens)
            key_mask = self._prefill_key_mask(P, pad_lens)
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, sl):
            q, k, v = self._block_qkv(sl, carry)
            att = flash_attention(q, k, v, causal=True, key_mask=key_mask)
            return self._block_post_attn(sl, carry, att), (k, v)

        h, (ks, vs) = jax.lax.scan(body, h, stacked)
        if getattr(c, "kv_cache_dtype", None) == "int8":
            def padq(x):
                q, s = quantize_kv(x)
                pad5 = [(0, 0), (0, 0), (0, max_len - P), (0, 0), (0, 0)]
                return (jnp.pad(q, pad5), jnp.pad(s, pad5[:-1]))
            return h, (padq(ks), padq(vs))
        pad = [(0, 0), (0, 0), (0, max_len - P), (0, 0), (0, 0)]
        dt = jnp.dtype(c.compute_dtype)
        return h, (jnp.pad(ks.astype(dt), pad), jnp.pad(vs.astype(dt), pad))

    def _block_decode_ragged(self, sl, h, pck, pcv, table, row_seq,
                             row_pos, pad_lens):
        """One block for a flattened ragged pack: h (1, T, H); pck/pcv are
        this layer's block pools (NB+1, bs, nh, hd).  Each row's k/v is
        scattered to its table-mapped pool position BEFORE attention, so
        intra-pack causal attention (a prefill chunk's rows attending each
        other) reads the freshly written keys — the _block_decode
        write-then-attend order over the ragged layout."""
        q, k, v = self._block_qkv(sl, h)               # (1, T, nh, hd)
        pck = ragged_write(pck, k[0], table, row_seq, row_pos)
        pcv = ragged_write(pcv, v[0], table, row_seq, row_pos)
        att = ragged_attention(q[0], pck, pcv, table, row_seq, row_pos,
                               pad_lens)
        return self._block_post_attn(sl, h, att[None]), pck, pcv

    def decode_ragged(self, params, h, pools, table, row_seq, row_pos,
                      pad_lens):
        """All blocks for one mixed ragged step (the serving engine's
        fused prefill+decode tick): h (1, T, H) from _embed_ragged,
        ``pools`` = (pool_ck, pool_cv) stacked over layers (int8
        ``(values, scales)`` pairs included), table (S, C) shared across
        layers, row metadata per ops/ragged_paged_attention.ragged_rows.
        Returns (h_out, pools).

        Speculative VERIFY chunks are just another ragged row group: a
        slot's [prev, d_0..d_{K-1}] rows at kv positions [t, t+K] ride
        the same write-then-attend order (each draft row attends its
        predecessors' freshly written k/v), so the ragged spec engine
        needs no separate verify program — the pack IS the verify."""
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, xs):
            sl, pck, pcv = xs
            out, pck, pcv = self._block_decode_ragged(
                sl, carry, pck, pcv, table, row_seq, row_pos, pad_lens)
            return out, (pck, pcv)

        h, (cks, cvs) = jax.lax.scan(body, h, (stacked, pools[0], pools[1]))
        return h, (cks, cvs)

    def decode_step(self, params, h, caches, t, pad_lens=None):
        """All blocks for one token: h (B,1,H), caches = (ck, cv) stacked
        over layers.  Returns (h_out, caches)."""
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, xs):
            sl, ck, cv = xs
            out, ck, cv = self._block_decode(sl, carry, ck, cv, t,
                                             pad_lens=pad_lens)
            return out, (ck, cv)

        h, (cks, cvs) = jax.lax.scan(body, h, (stacked, caches[0], caches[1]))
        return h, (cks, cvs)


class GPTForPretraining(GPTModel):
    """LM-head + loss (reference: GPTForPretraining in the fleet tests)."""

    def forward(self, input_ids, labels=None, **kw):
        logits = super().forward(input_ids, **kw)
        if labels is None:
            return logits
        raw_logits = getattr(logits, "_data", logits)
        raw_labels = getattr(labels, "_data", labels)
        logp = jax.nn.log_softmax(raw_logits, axis=-1)
        loss = -jnp.take_along_axis(logp, raw_labels[..., None], axis=-1).mean()
        return Tensor(loss) if isinstance(input_ids, Tensor) else loss


def gpt_preset(name: str, **overrides) -> GPTConfig:
    cfg = dict(GPT_CONFIGS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


def make_gpt_train_step(model: GPTModel, optimizer, hcg, n_microbatches: int = 1,
                        remat: bool = True, donate: bool = True,
                        zero_stage: int = 0, dynamic_loss_scale: bool = False,
                        virtual_pp_degree: Optional[int] = None,
                        monitor=None, grad_comm=None,
                        update_sharding: bool = False):
    """Build the full hybrid train step for GPT over the mesh.

    dp/mp/sharding/sep via GSPMD; pp via the stacked shard_map pipeline when
    the mesh has pipe>1.  step(state, key, lr, input_ids, labels) -> (state, loss).
    zero_stage>0 routes through the contractual ZeRO step (distributed/zero.py:
    grad reduce-scatter at stage 2, sharded params at stage 3, fp32 masters +
    found_inf + dynamic loss scaling — ≙ sharding_optimizer.py:45 semantics).
    ``monitor``: optional ``telemetry.TrainMonitor``, forwarded to the
    underlying builder (pipeline/zero) or wrapped around the GSPMD step —
    pure host-side timing, compiled programs identical either way.
    ``grad_comm``: gradient-communication policy ("fp32"/"bf16"/"int8_ef"
    or a ``distributed.grad_comm.GradCommPolicy``), forwarded to the zero
    or GSPMD builder; not wired for pp_degree>1 (the pipeline step owns
    its own exchange schedule).
    ``update_sharding``: on a plain data-parallel mesh, shard the weight
    update over the replicas (arXiv:2004.13336 via
    ``distributed.update_sharding``): optimizer-state HBM and update
    FLOPs per replica drop ~dp_degree×, token/loss-parity with the
    replicated update.  Mutually exclusive with zero_stage>0, pp>1, and
    sequence_parallel (those regimes own their own state layouts).
    """
    from ..distributed.grad_comm import comm_info, resolve_policy
    from ..distributed.pipeline_engine import make_stacked_pipeline_step
    from ..distributed.sharding_rules import activation_batch_spec
    from ..distributed.spmd import make_gspmd_step_from_loss
    from jax.sharding import NamedSharding

    policy = resolve_policy(grad_comm)
    mesh = hcg.mesh
    params0 = {n: p._data for n, p in model.named_parameters()}
    S = mesh.shape.get("pipe", 1)
    sp_mode = getattr(model.config, "sequence_parallel", None)
    sp_mesh = mesh if (sp_mode and mesh.shape.get("sep", 1) > 1) else None

    if S > 1:
        if policy.name != "fp32":
            raise NotImplementedError(
                "grad_comm with pp_degree>1 is not wired yet: the stacked "
                "pipeline step owns its own exchange schedule; use "
                "pp_degree=1 for compressed gradient collectives")
        if zero_stage > 0 or dynamic_loss_scale:
            raise NotImplementedError(
                "zero_stage/dynamic_loss_scale with pp_degree>1 is not wired "
                "yet: the stacked pipeline step manages its own state layout. "
                "Use pp_degree=1 for ZeRO, or sharding via the pipeline's own "
                "slot sharding (build_state_shardings).")
        if sp_mesh is not None:
            raise ValueError(
                "sequence_parallel with pp_degree>1 is not supported yet: the "
                "pipeline engine's shard_map over 'pipe' cannot nest the "
                "'sep' shard_map region; set sep_degree=1 or pp_degree=1")
        if virtual_pp_degree is None:  # strategy pp_configs default
            getter = getattr(hcg, "get_virtual_pipeline_degree", None)
            virtual_pp_degree = getter() if getter else 1
        return make_stacked_pipeline_step(
            model.embed_fn, model.block_fn, model.head_loss_fn, params0,
            optimizer, hcg, model.config.num_layers,
            max(n_microbatches, S), model.stacked_param_names(), layer=model,
            donate=donate, remat=remat, virtual_pp_degree=virtual_pp_degree,
            monitor=monitor)

    seq_spec = activation_batch_spec(mesh)

    def loss_of(params, key, x, labels):
        h = model.embed_fn(params, x, key)
        if seq_spec is not None:
            h = jax.lax.with_sharding_constraint(h, NamedSharding(mesh, seq_spec))
        h = model.scan_blocks(params, h, key, remat=remat, sp_mesh=sp_mesh)
        return model.head_loss_fn(params, h, labels)

    raw_step = None
    if zero_stage > 0:
        if update_sharding:
            raise ValueError(
                "update_sharding composes the plain-DP regime; zero_stage>0 "
                "already shards the optimizer state over 'sharding' — pick "
                "one")
        from ..distributed.zero import make_zero_train_step
        inner_step, state0 = make_zero_train_step(
            loss_of, params0, optimizer, mesh, layer=model,
            zero_stage=zero_stage, dynamic_loss_scale=dynamic_loss_scale,
            donate=donate, monitor=monitor, grad_comm=policy)
    elif update_sharding:
        if sp_mesh is not None:
            raise NotImplementedError(
                "update_sharding with sequence_parallel is not wired: the "
                "dp shard_map cannot nest the 'sep' shard_map region")
        from ..distributed.update_sharding import \
            make_dp_update_sharded_train_step

        # inside the dp shard_map the batch is already local — no GSPMD
        # activation constraint to thread (seq_spec is a GSPMD-path hint)
        def loss_of_local(params, key, x, labels):
            h = model.embed_fn(params, x, key)
            h = model.scan_blocks(params, h, key, remat=remat)
            return model.head_loss_fn(params, h, labels)

        # batch layout: (key, x, labels) — the key rides replicated
        inner_step, state0 = make_dp_update_sharded_train_step(
            loss_of_local, params0, optimizer, mesh, donate=donate,
            monitor=monitor, grad_comm=policy, replicated_args=(0,))
    else:
        from ..telemetry import instrument_train_step
        raw_step, state0 = make_gspmd_step_from_loss(
            loss_of, params0, optimizer, mesh, layer=model, donate=donate,
            grad_comm=policy)
        inner_step = instrument_train_step(raw_step, monitor, "gpt",
                                           comm=comm_info(params0, policy))

    def step(state, key, lr, x, labels):
        return inner_step(state, lr, key, x, labels)

    if raw_step is not None:
        # AOT seam (jit.functional.warm_train_step): an outer-order alias
        # of the same program — jit-of-jit inlines at trace time, so the
        # lowered/compiled executable is callable with step's PUBLIC
        # signature (the bare pre-instrument step is traced: the monitor
        # wrapper's host timing must never run under tracing)
        step.lower = jax.jit(
            lambda state, key, lr, x, labels: raw_step(
                state, lr, key, x, labels),
            donate_argnums=(0,) if donate else ()).lower
    else:
        # the zero step's bare program is not reachable from here, and
        # compile_aot's jax.jit fallback would trace the monitor wrapper
        # (corrupting its first-call compile accounting) — refuse loudly
        def _no_lower(*args, **kwargs):
            raise NotImplementedError(
                "AOT lowering for zero_stage>0 / update_sharding gpt steps "
                "is not wired (those builders own their state layouts); "
                "warm the plain GSPMD path, or rely on jit.aot."
                "enable_persistent_compilation_cache for cross-process "
                "reuse")
        step.lower = _no_lower

    return step, state0


def make_sharded_gpt_train_step(cfg: GPTConfig, optimizer, hcg,
                                zero_stage: int = 0, seed: int = 0,
                                remat=True, donate: bool = True,
                                monitor=None, grad_comm=None):
    """GPT train step whose parameters are initialized DIRECTLY sharded on
    the mesh — no host-side full-size materialization (GPT-3 6.7B fp32
    params are ~27GB on host with eager init).  Non-pipeline meshes only;
    use make_gpt_train_step for pp_degree > 1.

    ``zero_stage`` here means sharding SPECS only (params/slots partitioned
    over the "sharding" axis); the contractual ZeRO extras — fp32 masters,
    found_inf, dynamic loss scaling — live in make_gpt_train_step's
    make_zero_train_step route and are NOT applied on this path.

    ``grad_comm``: gradient-communication policy (``"fp32"`` / ``"bf16"``
    / ``"int8_ef"``), applied at the post-backward seam of the GSPMD step
    (LOCAL mode — see distributed/grad_comm.py); stateful policies add a
    flat ``"comm_e"`` residual leaf to the sharded TrainState.

    Returns ``(step, state0)`` with ``step(state, lr, key, x, labels)``.
    """
    from ..core import rng as _rng
    from ..distributed.grad_comm import comm_info, resolve_policy
    from ..distributed.spmd import make_gspmd_sharded_init_step

    mesh = hcg.mesh
    if mesh.shape.get("pipe", 1) > 1:
        raise NotImplementedError("sharded init with pp_degree>1: use "
                                  "make_gpt_train_step")
    if cfg.sequence_parallel is not None:
        raise NotImplementedError(
            "sharded init does not wire sequence_parallel yet — ring/Ulysses "
            "attention would silently fall back to gathered sequences; use "
            "make_gpt_train_step for sep meshes")
    holder = {}

    def build(key):
        with _rng.rng_scope(key):
            m = GPTModel(cfg)
        holder.setdefault("model", m)
        return {n: p._data for n, p in m.named_parameters()}

    jax.eval_shape(build, jax.random.key(seed))  # captures metadata model
    meta_model = holder["model"]  # params hold dead tracers; metadata + pure fns only

    def loss_of(params, key, x, labels):
        h = meta_model.embed_fn(params, x, key)
        h = meta_model.scan_blocks(params, h, key, remat=remat)
        return meta_model.head_loss_fn(params, h, labels)

    from ..telemetry import instrument_train_step
    policy = resolve_policy(grad_comm)
    step, state0 = make_gspmd_sharded_init_step(
        loss_of, build, optimizer, mesh, meta_model, zero_stage=zero_stage,
        donate=donate, seed=seed, grad_comm=policy)
    return instrument_train_step(
        step, monitor, "gpt_sharded",
        comm=comm_info(state0["params"], policy)), state0
