"""GPT model family — the flagship for hybrid-parallel training.

Reference capability: the fleet hybrid-parallel GPT tests
(hybrid_parallel_pp_transformer.py, GPT-3 configs in BASELINE.json).

TPU-first design decisions:
- **Stacked blocks**: all L transformer blocks live in ONE pytree with a
  leading layer dim, consumed by ``lax.scan`` — one compiled block program
  regardless of depth (compile time O(1) in L), and the leading dim is the
  natural pipeline-stage shard ("pipe") for the shard_map pipeline engine.
- **TP via dims_mapping**: qkv/fc1 are column-parallel (out dim on "model"),
  proj/fc2 row-parallel (in dim on "model") — GSPMD inserts the allreduces
  the reference's ColumnParallelLinear/RowParallelLinear issue explicitly.
- **Sequence parallel**: activations constrained to P("data", "sep", None)
  between blocks when a "sep" axis exists.
- **bf16 compute, fp32 params** by default; flash attention from
  paddle_tpu.ops (Pallas on TPU).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.tensor import Parameter, Tensor, apply
from ..nn.layer.base import Layer
from ..ops.attention import flash_attention


def cached_attention(q, ck, cv, t):
    """Single-query attention against a static KV cache, masked to positions
    ≤ t (slots beyond t hold zeros or stale values).  q (B, 1, nh, hd);
    ck/cv (B, max_len, nh, hd).  Shared by the GPT and ERNIE-MoE decode
    paths so the mask/scale/precision conventions cannot drift."""
    hd = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)).astype(q.dtype)
    mask = jnp.arange(ck.shape[1]) <= t
    scores = jnp.where(mask[None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)


def make_token_sampler(temperature, top_k, top_p, greedy):
    """Shared last-position sampler for the decode loops (GPT + ERNIE-MoE):
    temperature → optional top-k filter → optional nucleus (top-p) filter →
    argmax or categorical.  ``logits32`` is (B, 1, V) fp32."""
    def sample(logits32, key):
        logits32 = logits32[:, -1, :] / jnp.asarray(
            max(temperature, 1e-6), jnp.float32)
        if top_k is not None:
            vals, _ = jax.lax.top_k(logits32, top_k)
            logits32 = jnp.where(logits32 < vals[:, -1:], -jnp.inf, logits32)
        if top_p is not None:
            # nucleus: keep the smallest prefix of the sorted vocab with
            # cumulative probability ≥ top_p (the boundary token stays)
            srt = jnp.sort(logits32, -1)[:, ::-1]
            cdf = jnp.cumsum(jax.nn.softmax(srt, -1), -1)
            n_keep = jnp.sum(cdf < top_p, -1) + 1
            kth = jnp.take_along_axis(srt, (n_keep - 1)[:, None], 1)
            logits32 = jnp.where(logits32 < kth, -jnp.inf, logits32)
        if greedy:
            return jnp.argmax(logits32, -1).astype(jnp.int32)
        return jax.random.categorical(key, logits32, -1).astype(jnp.int32)
    return sample


def validate_sampler_args(vocab_size, top_k, top_p, greedy, key):
    """Common generate() argument validation (fail before tracing)."""
    if not greedy and key is None:
        raise ValueError("sampling (greedy=False) requires key")
    if top_k is not None and not 1 <= int(top_k) <= vocab_size:
        raise ValueError(f"top_k must be in [1, vocab_size={vocab_size}], "
                         f"got {top_k}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0, initializer_range=0.02,
                 layer_norm_epsilon=1e-5, compute_dtype="bfloat16",
                 use_flash_attention=True, tie_word_embeddings=True,
                 sequence_parallel=None, scan_unroll=1,
                 hidden_act="gelu_approx"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.compute_dtype = compute_dtype
        self.use_flash_attention = use_flash_attention
        self.tie_word_embeddings = tie_word_embeddings
        self.scan_unroll = scan_unroll  # layers per scan step (see scan_blocks)
        # GPT-2's canonical activation is the tanh approximation ("gelu_new")
        # — hence the approx default; "gelu" selects the exact erf form
        if hidden_act not in ("gelu", "gelu_approx"):
            raise ValueError(f"hidden_act must be 'gelu' or 'gelu_approx', "
                             f"got {hidden_act!r}")
        self.hidden_act = hidden_act
        # None → GSPMD decides (sequence gathered for attention);
        # "ring"/"ulysses" → explicit context parallelism over the "sep" axis
        if sequence_parallel not in (None, "ring", "ulysses"):
            raise ValueError(f"sequence_parallel must be None, 'ring' or "
                             f"'ulysses', got {sequence_parallel!r}")
        self.sequence_parallel = sequence_parallel


# canonical sizes (GPT-3 paper / fleet configs)
GPT_CONFIGS = {
    "gpt2-small": dict(hidden_size=768, num_layers=12, num_attention_heads=12),
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_attention_heads=16),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_attention_heads=20),
    "gpt3-1.3B": dict(hidden_size=2048, num_layers=24, num_attention_heads=16),
    "gpt3-2.7B": dict(hidden_size=2560, num_layers=32, num_attention_heads=32),
    "gpt3-6.7B": dict(hidden_size=4096, num_layers=32, num_attention_heads=32),
    "gpt3-13B": dict(hidden_size=5120, num_layers=40, num_attention_heads=40),
}


class GPTModel(Layer):
    """Decoder-only transformer with stacked block parameters."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = c = config
        L, H, V = c.num_layers, c.hidden_size, c.vocab_size
        I = c.intermediate_size
        std = c.initializer_range

        def normal(shape, s=std):
            from ..nn.initializer import Normal
            return Normal(0.0, s)(shape, "float32")

        def zeros(shape):
            return jnp.zeros(shape, jnp.float32)

        def ones(shape):
            return jnp.ones(shape, jnp.float32)

        def param(name, data, mapping=None):
            p = Parameter(data, name=name)
            if mapping:
                p._dims_mapping = mapping
            self.add_parameter(name.replace(".", "_"), p)
            return p

        # embeddings (vocab-parallel like VocabParallelEmbedding)
        self.wte = param("wte", normal([V, H]), {0: "model"})
        self.wpe = param("wpe", normal([c.max_position_embeddings, H]))
        # stacked blocks — column-parallel qkv/fc1, row-parallel proj/fc2
        # (reference: fused_attention_op.cu QKV fused gemm; fleet mp_layers)
        self.blocks_ln1_w = param("blocks.ln1_w", ones([L, H]))
        self.blocks_ln1_b = param("blocks.ln1_b", zeros([L, H]))
        self.blocks_qkv_w = param("blocks.qkv_w", normal([L, H, 3 * H]), {2: "model"})
        self.blocks_qkv_b = param("blocks.qkv_b", zeros([L, 3 * H]), {1: "model"})
        self.blocks_proj_w = param("blocks.proj_w",
                                   normal([L, H, H], std / math.sqrt(2 * L)),
                                   {1: "model"})
        self.blocks_proj_b = param("blocks.proj_b", zeros([L, H]))
        self.blocks_ln2_w = param("blocks.ln2_w", ones([L, H]))
        self.blocks_ln2_b = param("blocks.ln2_b", zeros([L, H]))
        self.blocks_fc1_w = param("blocks.fc1_w", normal([L, H, I]), {2: "model"})
        self.blocks_fc1_b = param("blocks.fc1_b", zeros([L, I]), {1: "model"})
        self.blocks_fc2_w = param("blocks.fc2_w",
                                  normal([L, I, H], std / math.sqrt(2 * L)),
                                  {1: "model"})
        self.blocks_fc2_b = param("blocks.fc2_b", zeros([L, H]))
        self.lnf_w = param("lnf_w", ones([H]))
        self.lnf_b = param("lnf_b", zeros([H]))
        if not c.tie_word_embeddings:
            self.lm_head = param("lm_head", normal([H, V]), {1: "model"})

    # -------------------------------------------------------- pure functions
    @staticmethod
    def stacked_param_names():
        return [f"blocks_{n}" for n in ("ln1_w", "ln1_b", "qkv_w", "qkv_b",
                                        "proj_w", "proj_b", "ln2_w", "ln2_b",
                                        "fc1_w", "fc1_b", "fc2_w", "fc2_b")]

    def embed_fn(self, params: Dict[str, Any], input_ids, key=None):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        pos = jnp.arange(input_ids.shape[-1])
        h = jnp.take(params["wte"], input_ids, axis=0) + params["wpe"][pos]
        return h.astype(dt)

    def _block_ln(self, x, w, b, dt):
        x32 = x.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        return ((x32 - m) * jax.lax.rsqrt(v + self.config.layer_norm_epsilon)
                * w + b).astype(dt)

    def _block_qkv(self, sl, h):
        """pre-LN + QKV projection; returns q, k, v as (B, L, nh, hd)."""
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        nh = c.num_attention_heads
        hd = H // nh
        a_in = self._block_ln(h, sl["blocks_ln1_w"], sl["blocks_ln1_b"], dt)
        qkv = a_in @ sl["blocks_qkv_w"].astype(dt) + sl["blocks_qkv_b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        return (q.reshape(B, Lq, nh, hd), k.reshape(B, Lq, nh, hd),
                v.reshape(B, Lq, nh, hd))

    def _block_post_attn(self, sl, h, att):
        """attention output projection + residual + MLP half of the block."""
        dt = h.dtype
        B, Lq, H = h.shape
        att = att.reshape(B, Lq, H)
        h = h + att @ sl["blocks_proj_w"].astype(dt) + sl["blocks_proj_b"].astype(dt)
        m_in = self._block_ln(h, sl["blocks_ln2_w"], sl["blocks_ln2_b"], dt)
        ff = jax.nn.gelu(m_in @ sl["blocks_fc1_w"].astype(dt)
                         + sl["blocks_fc1_b"].astype(dt),
                         approximate=self.config.hidden_act == "gelu_approx")
        return h + ff @ sl["blocks_fc2_w"].astype(dt) + sl["blocks_fc2_b"].astype(dt)

    def block_fn(self, sl: Dict[str, Any], h, key=None, sp_mesh=None):
        """One transformer block given this layer's parameter slice.

        ``sp_mesh``: when set (by make_gpt_train_step on a mesh with sep>1)
        attention runs as explicit ring/Ulysses context parallelism over the
        "sep" axis instead of letting GSPMD gather the sequence."""
        c = self.config
        B, Lq, H = h.shape
        q, k, v = self._block_qkv(sl, h)
        sp_mode = getattr(c, "sequence_parallel", None)
        mesh = sp_mesh
        if sp_mode and mesh is not None and mesh.shape.get("sep", 1) > 1:
            if Lq % mesh.shape["sep"] != 0:
                # never fall back silently — gathered attention is exactly the
                # O(L) per-device memory blowup the user opted out of
                raise ValueError(
                    f"sequence_parallel={sp_mode!r} needs seq_len ({Lq}) "
                    f"divisible by the sep degree ({mesh.shape['sep']}); pad "
                    f"the sequence or change sep_degree")
            # context parallelism: activations stay sequence-sharded on "sep";
            # ring/Ulysses attention inside a partial-manual shard_map region
            # (only "sep" is manual — dp/mp stay under GSPMD)
            from jax.sharding import PartitionSpec as P
            from ..ops.ring_attention import sequence_parallel_attention
            att = jax.shard_map(
                functools.partial(sequence_parallel_attention, axis_name="sep",
                                  causal=True, mode=sp_mode),
                mesh=mesh, in_specs=P(None, "sep", None, None),
                out_specs=P(None, "sep", None, None), axis_names={"sep"},
            )(q, k, v)
        else:
            att = flash_attention(q, k, v, causal=True)
        return self._block_post_attn(sl, h, att)

    def _head_logits(self, params: Dict[str, Any], h):
        c = self.config
        x32 = h.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        h = (x32 - m) * jax.lax.rsqrt(v + c.layer_norm_epsilon) * params["lnf_w"] \
            + params["lnf_b"]
        w = params.get("lm_head")
        if w is None:
            w = params["wte"].T
        dt = jnp.dtype(c.compute_dtype)
        return h.astype(dt) @ w.astype(dt)

    def head_fn(self, params: Dict[str, Any], h):
        return self._head_logits(params, h).astype(jnp.float32)

    def head_loss_fn(self, params: Dict[str, Any], h, labels):
        # fused CE on compute-dtype logits: never materializes the fp32
        # (B, L, V) log-prob tensor (ops/loss.py — ≙ the reference's fused
        # softmax_with_cross_entropy, operators/math/cross_entropy.cu)
        from ..ops.loss import softmax_cross_entropy_mean
        return softmax_cross_entropy_mean(self._head_logits(params, h), labels)

    def scan_blocks(self, params, h, key=None, remat=True, sp_mesh=None):
        """``remat``: False = save all activations; True = full per-block
        recompute (≙ RecomputeOptimizer, fluid/optimizer.py:5930); "dots" =
        selective policy that saves MXU (matmul) outputs and recomputes only
        elementwise interiors — near-full-speed backward at a fraction of the
        activation memory (the TPU-idiomatic default for large batches)."""
        stacked = {k: params[k] for k in self.stacked_param_names()}
        if remat:
            policy = None
            if remat == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            fn = jax.checkpoint(
                lambda sl, hh: self.block_fn(sl, hh, key, sp_mesh=sp_mesh),
                policy=policy)

            def body(carry, sl):
                return fn(sl, carry), None
        else:
            def body(carry, sl):
                return self.block_fn(sl, carry, key, sp_mesh=sp_mesh), None
        from ._scan import resolve_scan_unroll
        out, _ = jax.lax.scan(body, h, stacked,
                              unroll=resolve_scan_unroll(self.config))
        return out

    # ------------------------------------------------------------- nn.Layer
    def forward(self, input_ids, position_ids=None, attention_mask=None,
                use_cache=False, cache=None):
        raw = getattr(input_ids, "_data", input_ids)
        params = {n: p._data for n, p in self.named_parameters()}
        h = self.embed_fn(params, raw)
        h = self.scan_blocks(params, h, remat=False)
        logits = self.head_fn(params, h)
        return Tensor(logits) if isinstance(input_ids, Tensor) else logits

    # ------------------------------------------------- KV-cache generation
    # ≙ the reference ecosystem's generation stack (paddlenlp generation_
    # utils; fused_multi_transformer_op's CacheKV).  TPU-native shape: the
    # cache is a STATIC (num_layers, B, max_len, nh, hd) buffer written with
    # dynamic_update_slice, the decode loop is one lax.scan — a single XLA
    # program regardless of how many tokens are generated.

    def _block_decode(self, sl, h, ck, cv, t):
        """One block for ONE new token at position ``t``.

        h (B, 1, H); ck/cv (B, max_len, nh, hd) are this layer's caches.
        Returns (h_out, ck, cv) with the new k/v written at index t and
        attention taken over cache positions ≤ t (later slots hold zeros or
        stale values and are masked)."""
        q, k, v = self._block_qkv(sl, h)
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, t, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, t, 0, 0))
        att = cached_attention(q, ck, cv, t)
        return self._block_post_attn(sl, h, att), ck, cv

    def _embed_one(self, params, tok, t):
        """Embed one token per row at position ``t``: (B,) -> (B, 1, H)."""
        dt = jnp.dtype(self.config.compute_dtype)
        return (jnp.take(params["wte"], tok[:, None], axis=0)
                + params["wpe"][t][None, None, :]).astype(dt)

    def init_cache(self, batch_size: int, max_len: int):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        nh = c.num_attention_heads
        hd = c.hidden_size // nh
        shape = (c.num_layers, batch_size, max_len, nh, hd)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def prefill(self, params, input_ids, max_len: int):
        """Run the prompt through all blocks, returning the final hidden
        states (B, P, H) and caches filled at positions [0, P)."""
        c = self.config
        B, P = input_ids.shape
        h = self.embed_fn(params, input_ids)
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, sl):
            q, k, v = self._block_qkv(sl, carry)
            att = flash_attention(q, k, v, causal=True)
            return self._block_post_attn(sl, carry, att), (k, v)

        h, (ks, vs) = jax.lax.scan(body, h, stacked)
        pad = [(0, 0), (0, 0), (0, max_len - P), (0, 0), (0, 0)]
        dt = jnp.dtype(c.compute_dtype)
        return h, (jnp.pad(ks.astype(dt), pad), jnp.pad(vs.astype(dt), pad))

    def decode_step(self, params, h, caches, t):
        """All blocks for one token: h (B,1,H), caches = (ck, cv) stacked
        over layers.  Returns (h_out, caches)."""
        stacked = {k: params[k] for k in self.stacked_param_names()}

        def body(carry, xs):
            sl, ck, cv = xs
            out, ck, cv = self._block_decode(sl, carry, ck, cv, t)
            return out, (ck, cv)

        h, (cks, cvs) = jax.lax.scan(body, h, (stacked, caches[0], caches[1]))
        return h, (cks, cvs)

    def generate(self, params, input_ids, max_new_tokens: int,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, greedy: bool = True, key=None):
        """Autoregressive generation with a static KV cache.

        input_ids (B, P) int32; returns (B, max_new_tokens) generated ids.
        greedy=True → argmax decoding; else temperature (+ optional top-k
        and/or nucleus top-p) sampling with ``key``.  The whole decode loop
        is ONE compiled program per (P, max_new_tokens, temperature, top_k,
        top_p, greedy) signature, memoized on the model — vary only the
        prompt content (and bucket P via paddle.jit.bucketize) for serving
        cache hits.
        """
        c = self.config
        B, P = input_ids.shape
        if max_new_tokens <= 0:
            return jnp.zeros((B, 0), jnp.int32)
        max_len = P + max_new_tokens
        if max_len > c.max_position_embeddings:
            raise ValueError(f"P + max_new_tokens = {max_len} exceeds "
                             f"max_position_embeddings ({c.max_position_embeddings})")
        validate_sampler_args(c.vocab_size, top_k, top_p, greedy, key)
        key = jax.random.key(0) if key is None else key
        run = self._gen_program(P, max_new_tokens, float(temperature),
                                None if top_k is None else int(top_k),
                                None if top_p is None else float(top_p),
                                greedy)
        return run(params, jnp.asarray(input_ids), key)

    def _gen_program(self, P, max_new_tokens, temperature, top_k, top_p,
                     greedy):
        """Build (and memoize) the jitted prefill+decode program for one
        (P, max_new_tokens, temperature, top_k, top_p, greedy) signature —
        repeated generate() calls with the same signature hit the jit cache
        instead of recompiling the whole model."""
        cache_key = (P, max_new_tokens, temperature, top_k, top_p, greedy)
        progs = self.__dict__.setdefault("_gen_programs", {})
        if cache_key in progs:
            return progs[cache_key]
        max_len = P + max_new_tokens
        sample = make_token_sampler(temperature, top_k, top_p, greedy)

        @jax.jit
        def run(params, input_ids, key):
            h, caches = self.prefill(params, input_ids, max_len)
            key, k0 = jax.random.split(key)
            tok0 = sample(self.head_fn(params, h[:, -1:]), k0)

            def body(carry, i):
                tok, caches, key = carry
                t = P + i  # this token's position in the cache
                h = self._embed_one(params, tok, t)
                h, caches = self.decode_step(params, h, caches, t)
                key, sub = jax.random.split(key)
                ntok = sample(self.head_fn(params, h), sub)
                return (ntok, caches, key), ntok

            (last, _, _), toks = jax.lax.scan(
                body, (tok0, caches, key), jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([tok0[:, None], toks.T], axis=1)

        progs[cache_key] = run
        return run

    def generate_beam(self, params, input_ids, max_new_tokens: int,
                      num_beams: int = 4, length_penalty: float = 1.0,
                      eos_token_id: Optional[int] = None):
        """Beam-search decoding on the KV cache (≙ generation_utils
        BeamSearchScorer semantics, fixed length budget).

        Returns (sequences (B, max_new_tokens), scores (B,)) for the best
        beam per batch row; ``scores`` are summed log-probs divided by
        length**length_penalty.  ``eos_token_id``: beams that emit EOS are
        frozen (EOS repeats, log-prob stops accumulating) so shorter
        hypotheses compete under the penalty.

        TPU shape: beams fold into the batch dim (B*K), the cache reorder is
        one take_along_axis per step, and the whole search is a single
        lax.scan — no dynamic shapes, no host sync inside the loop.
        """
        c = self.config
        B, P = input_ids.shape
        K = int(num_beams)
        if not 1 <= K <= c.vocab_size:
            raise ValueError(f"num_beams must be in [1, vocab_size="
                             f"{c.vocab_size}], got {num_beams}")
        if eos_token_id is not None and not 0 <= eos_token_id < c.vocab_size:
            raise ValueError(f"eos_token_id {eos_token_id} outside the vocab "
                             f"[0, {c.vocab_size}) — EOS freezing would "
                             f"silently never trigger")
        if max_new_tokens <= 0:
            return jnp.zeros((B, 0), jnp.int32), jnp.zeros((B,), jnp.float32)
        max_len = P + max_new_tokens
        if max_len > c.max_position_embeddings:
            raise ValueError(f"P + max_new_tokens = {max_len} exceeds "
                             f"max_position_embeddings ({c.max_position_embeddings})")
        run = self._beam_program(P, max_new_tokens, K, float(length_penalty),
                                 eos_token_id)
        return run(params, jnp.asarray(input_ids))

    def _beam_program(self, P, max_new_tokens, K, length_penalty,
                      eos_token_id):
        cache_key = ("beam", P, max_new_tokens, K, length_penalty,
                     eos_token_id)
        progs = self.__dict__.setdefault("_gen_programs", {})
        if cache_key in progs:
            return progs[cache_key]
        c = self.config
        max_len = P + max_new_tokens
        V = c.vocab_size
        NEG = jnp.float32(-1e30)

        def logprobs_last(params, h):
            return jax.nn.log_softmax(
                self.head_fn(params, h)[:, -1, :].astype(jnp.float32), -1)

        @jax.jit
        def run(params, input_ids):
            B = input_ids.shape[0]
            h, caches = self.prefill(params, input_ids, max_len)
            lp0 = logprobs_last(params, h)                      # (B, V)
            # beams start identical: only beam 0 is live at step 0
            top_lp, top_tok = jax.lax.top_k(lp0, K)             # (B, K)
            cum = top_lp
            if eos_token_id is not None:
                finished0 = top_tok == eos_token_id
            else:
                finished0 = jnp.zeros((B, K), bool)
            # per-beam hypothesis length (tokens incl. EOS): finished beams
            # keep the length at which they finished so the length penalty
            # ranks short hypotheses correctly (BeamSearchScorer semantics)
            lengths0 = jnp.where(finished0, 1.0,
                                 float(max_new_tokens)).astype(jnp.float32)
            # tile caches per beam: (nl, B, ...) -> (nl, B*K, ...)
            caches = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, K, axis=1), caches)

            def body(carry, i):
                tok, caches, cum, finished, lengths = carry
                t = P + i
                hh = self._embed_one(params, tok, t)
                hh, caches = self.decode_step(params, hh, caches, t)
                lp = logprobs_last(params, hh).reshape(B, K, V)
                if eos_token_id is not None:
                    # frozen beams: only EOS continues, at zero cost
                    eos_only = jnp.full((V,), NEG).at[eos_token_id].set(0.0)
                    lp = jnp.where(finished[..., None], eos_only[None, None],
                                   lp)
                total = cum[..., None] + lp                      # (B, K, V)
                flat = total.reshape(B, K * V)
                cum, idx = jax.lax.top_k(flat, K)                # (B, K)
                parent = idx // V
                ntok = (idx % V).astype(jnp.int32)
                if eos_token_id is not None:
                    was = jnp.take_along_axis(finished, parent, axis=1)
                    lengths = jnp.take_along_axis(lengths, parent, axis=1)
                    newly = ~was & (ntok == eos_token_id)
                    # token emitted at body step i is hypothesis token i+2
                    lengths = jnp.where(newly, (i + 2).astype(jnp.float32),
                                        lengths)
                    finished = was | newly
                # reorder caches to the surviving beams
                def reorder(a):
                    nl = a.shape[0]
                    ab = a.reshape((nl, B, K) + a.shape[2:])
                    pidx = parent.reshape((1, B, K) + (1,) * (ab.ndim - 3))
                    return jnp.take_along_axis(ab, pidx, axis=2).reshape(a.shape)
                caches = jax.tree_util.tree_map(reorder, caches)
                tok = ntok.reshape(B * K)
                return (tok, caches, cum, finished, lengths), (ntok, parent)

            (_, _, cum, _, lengths), (toks, parents) = jax.lax.scan(
                body, (top_tok.reshape(B * K), caches, cum, finished0,
                       lengths0),
                jnp.arange(max_new_tokens - 1))

            # backtrace: walk parents from the best final beam to step 0
            scores = cum / jnp.power(lengths, length_penalty)
            best = jnp.argmax(scores, axis=1)                    # (B,)

            def back(k, step):
                st, sp = step                                    # (B,K) each
                tok_t = jnp.take_along_axis(st, k[:, None], 1)[:, 0]
                k = jnp.take_along_axis(sp, k[:, None], 1)[:, 0]
                return k, tok_t

            k_last, toks_rev = jax.lax.scan(
                back, best, (toks[::-1], parents[::-1]))
            first = jnp.take_along_axis(top_tok, k_last[:, None], 1)[:, 0]
            seq = jnp.concatenate([first[:, None], toks_rev[::-1].T], axis=1)
            best_score = jnp.take_along_axis(scores, best[:, None], 1)[:, 0]
            return seq, best_score

        progs[cache_key] = run
        return run


class GPTForPretraining(GPTModel):
    """LM-head + loss (reference: GPTForPretraining in the fleet tests)."""

    def forward(self, input_ids, labels=None, **kw):
        logits = super().forward(input_ids, **kw)
        if labels is None:
            return logits
        raw_logits = getattr(logits, "_data", logits)
        raw_labels = getattr(labels, "_data", labels)
        logp = jax.nn.log_softmax(raw_logits, axis=-1)
        loss = -jnp.take_along_axis(logp, raw_labels[..., None], axis=-1).mean()
        return Tensor(loss) if isinstance(input_ids, Tensor) else loss


def gpt_preset(name: str, **overrides) -> GPTConfig:
    cfg = dict(GPT_CONFIGS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


def make_gpt_train_step(model: GPTModel, optimizer, hcg, n_microbatches: int = 1,
                        remat: bool = True, donate: bool = True,
                        zero_stage: int = 0, dynamic_loss_scale: bool = False,
                        virtual_pp_degree: Optional[int] = None):
    """Build the full hybrid train step for GPT over the mesh.

    dp/mp/sharding/sep via GSPMD; pp via the stacked shard_map pipeline when
    the mesh has pipe>1.  step(state, key, lr, input_ids, labels) -> (state, loss).
    zero_stage>0 routes through the contractual ZeRO step (distributed/zero.py:
    grad reduce-scatter at stage 2, sharded params at stage 3, fp32 masters +
    found_inf + dynamic loss scaling — ≙ sharding_optimizer.py:45 semantics).
    """
    from ..distributed.pipeline_engine import make_stacked_pipeline_step
    from ..distributed.spmd import make_gspmd_step_from_loss
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = hcg.mesh
    params0 = {n: p._data for n, p in model.named_parameters()}
    S = mesh.shape.get("pipe", 1)
    sp_mode = getattr(model.config, "sequence_parallel", None)
    sp_mesh = mesh if (sp_mode and mesh.shape.get("sep", 1) > 1) else None

    if S > 1:
        if zero_stage > 0 or dynamic_loss_scale:
            raise NotImplementedError(
                "zero_stage/dynamic_loss_scale with pp_degree>1 is not wired "
                "yet: the stacked pipeline step manages its own state layout. "
                "Use pp_degree=1 for ZeRO, or sharding via the pipeline's own "
                "slot sharding (build_state_shardings).")
        if sp_mesh is not None:
            raise ValueError(
                "sequence_parallel with pp_degree>1 is not supported yet: the "
                "pipeline engine's shard_map over 'pipe' cannot nest the "
                "'sep' shard_map region; set sep_degree=1 or pp_degree=1")
        if virtual_pp_degree is None:  # strategy pp_configs default
            getter = getattr(hcg, "get_virtual_pipeline_degree", None)
            virtual_pp_degree = getter() if getter else 1
        return make_stacked_pipeline_step(
            model.embed_fn, model.block_fn, model.head_loss_fn, params0,
            optimizer, hcg, model.config.num_layers,
            max(n_microbatches, S), model.stacked_param_names(), layer=model,
            donate=donate, remat=remat, virtual_pp_degree=virtual_pp_degree)

    seq_spec = None
    if "sep" in mesh.shape and mesh.shape["sep"] > 1:
        seq_spec = P("data", "sep", None)
    elif "data" in mesh.shape and mesh.shape["data"] > 1:
        seq_spec = P("data", None, None)

    def loss_of(params, key, x, labels):
        h = model.embed_fn(params, x, key)
        if seq_spec is not None:
            h = jax.lax.with_sharding_constraint(h, NamedSharding(mesh, seq_spec))
        h = model.scan_blocks(params, h, key, remat=remat, sp_mesh=sp_mesh)
        return model.head_loss_fn(params, h, labels)

    if zero_stage > 0:
        from ..distributed.zero import make_zero_train_step
        inner_step, state0 = make_zero_train_step(
            loss_of, params0, optimizer, mesh, layer=model,
            zero_stage=zero_stage, dynamic_loss_scale=dynamic_loss_scale,
            donate=donate)
    else:
        inner_step, state0 = make_gspmd_step_from_loss(
            loss_of, params0, optimizer, mesh, layer=model, donate=donate)

    def step(state, key, lr, x, labels):
        return inner_step(state, lr, key, x, labels)

    return step, state0


def make_sharded_gpt_train_step(cfg: GPTConfig, optimizer, hcg,
                                zero_stage: int = 0, seed: int = 0,
                                remat=True, donate: bool = True):
    """GPT train step whose parameters are initialized DIRECTLY sharded on
    the mesh — no host-side full-size materialization (GPT-3 6.7B fp32
    params are ~27GB on host with eager init).  Non-pipeline meshes only;
    use make_gpt_train_step for pp_degree > 1.

    ``zero_stage`` here means sharding SPECS only (params/slots partitioned
    over the "sharding" axis); the contractual ZeRO extras — fp32 masters,
    found_inf, dynamic loss scaling — live in make_gpt_train_step's
    make_zero_train_step route and are NOT applied on this path.

    Returns ``(step, state0)`` with ``step(state, lr, key, x, labels)``.
    """
    from ..core import rng as _rng
    from ..distributed.spmd import make_gspmd_sharded_init_step

    mesh = hcg.mesh
    if mesh.shape.get("pipe", 1) > 1:
        raise NotImplementedError("sharded init with pp_degree>1: use "
                                  "make_gpt_train_step")
    if cfg.sequence_parallel is not None:
        raise NotImplementedError(
            "sharded init does not wire sequence_parallel yet — ring/Ulysses "
            "attention would silently fall back to gathered sequences; use "
            "make_gpt_train_step for sep meshes")
    holder = {}

    def build(key):
        with _rng.rng_scope(key):
            m = GPTModel(cfg)
        holder.setdefault("model", m)
        return {n: p._data for n, p in m.named_parameters()}

    jax.eval_shape(build, jax.random.key(seed))  # captures metadata model
    meta_model = holder["model"]  # params hold dead tracers; metadata + pure fns only

    def loss_of(params, key, x, labels):
        h = meta_model.embed_fn(params, x, key)
        h = meta_model.scan_blocks(params, h, key, remat=remat)
        return meta_model.head_loss_fn(params, h, labels)

    return make_gspmd_sharded_init_step(
        loss_of, build, optimizer, mesh, meta_model, zero_stage=zero_stage,
        donate=donate, seed=seed)
