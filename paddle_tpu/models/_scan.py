"""Shared helpers for the stacked-block ``lax.scan`` model skeleton."""

from __future__ import annotations


def resolve_scan_unroll(config) -> int:
    """Layers per scan step.  1 = rolled loop (O(1) compile in depth);
    num_layers = fully unrolled (no dynamic_slice/update HBM traffic — see
    BENCH_NOTES.md, ~11ms/step at gpt2s bench shapes)."""
    return max(1, int(getattr(config, "scan_unroll", 1) or 1))
