"""Shared KV-cache decode machinery for the causal LMs (GPT, ERNIE-MoE).

≙ the reference snapshot's incremental decode stack: MultiHeadAttention
.Cache/gen_cache k/v (python/paddle/nn/layer/transformer.py:151) +
dynamic_decode/BeamSearchDecoder (python/paddle/nn/decode.py) +
sampling_id/top_k ops (operators/sampling_id_op.cc).  (The later-Paddle
ecosystem's paddlenlp generation_utils / fused_multi_transformer CacheKV
are NOT in this snapshot.)  One module so the mask/scale/precision
conventions and the sampler cannot drift between model families.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cached_attention(q, ck, cv, t, pad_lens=None):
    """Attention for new tokens written at cache slots [t, t+k) against a
    static KV cache: query row i attends to positions ≤ t + i (causal within
    the chunk, full history before it; slots beyond hold zeros or stale
    values).  q (B, k, nh, hd) — k = 1 is the plain decode step, k > 1 is the
    chunk form used by speculative-decoding verification.  ``pad_lens`` (B,)
    int32 additionally masks the first pad_lens[b] cache slots (left-padded
    prompts).  Shared by the GPT and ERNIE-MoE decode paths so the mask/
    scale/precision conventions cannot drift."""
    if isinstance(ck, PagedKV):
        from ..core.flags import flag
        kernel_ok = (q.shape[1] == 1                 # the decode tick
                     and not isinstance(ck.pool, tuple))   # fp pools only
        # FLAGS_use_pallas_kernels stays the authoritative kill switch (the
        # ops/fused.py convention); the interpret arm applies only OFF-TPU
        # (CPU CI of the in-kernel table walk)
        interp = (bool(flag("FLAGS_paged_attn_interpret"))
                  and jax.default_backend() != "tpu")
        use = flag("FLAGS_use_pallas_kernels") and \
            (jax.default_backend() == "tpu" or interp)
        if kernel_ok and use:
            from ..ops.paged_attention import paged_decode_attention
            S = q.shape[0]
            t_vec = jnp.broadcast_to(jnp.asarray(t), (S,))
            pad_vec = (None if pad_lens is None
                       else jnp.broadcast_to(jnp.asarray(pad_lens), (S,)))
            o = paged_decode_attention(q[:, 0], ck.pool, cv.pool, ck.table,
                                       t_vec, pad_vec, interpret=interp)
            return o[:, None]
        # fallback: densify this layer's table-selected blocks
        ck = ck.gather(q.dtype)
        cv = cv.gather(q.dtype)
    kq = q.shape[1]
    hd = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)).astype(q.dtype)
    row = jnp.arange(kq)[:, None]
    col = jnp.arange(ck.shape[1])[None, :]
    t_arr = jnp.asarray(t)
    if t_arr.ndim == 0:                                # one slot for all rows
        mask = (col <= t_arr + row)[None, None]        # (1, 1, k, max_len)
    else:                                              # per-row slots (B,)
        mask = (col[None, None] <=
                t_arr[:, None, None, None] + row[None, None])
    if pad_lens is not None:
        pos = jnp.arange(ck.shape[1])
        mask = mask & (pos[None, :] >= pad_lens[:, None])[:, None, None, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)


@jax.tree_util.register_pytree_node_class
class PagedKV:
    """One k-or-v cache over a BLOCK POOL + slot block table (the serving
    engine's paged layout, flowing through the same decode code path as
    dense caches via dispatch in write_cache/cached_attention).

    ``pool``: (NB+1, bs, nh, hd) — or with a leading layer axis, which
    lax.scan over layers slices off; block 0 is the reserved trash block.
    int8 pools are (values, scales) pairs.  ``table``: (S, C) int32 —
    C table columns cover every ACTIVE row's positions; inactive rows'
    table rows must be pre-zeroed by the caller (their writes then land
    in trash even where the clamped column lookup would alias a real
    block).  As a pytree, scanning over layers slices pool and table
    together (the engine broadcasts the table across layers)."""

    def __init__(self, pool, table):
        self.pool = pool
        self.table = table

    def tree_flatten(self):
        return (self.pool, self.table), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def block_size(self):
        # axis 1 of the PER-LAYER pool is bs for BOTH planes — the value
        # plane is (NB+1, bs, nh, hd), the int8 scale plane (NB+1, bs, nh)
        # is one rank short, so a from-the-right index would be wrong
        vals = self.pool[0] if isinstance(self.pool, tuple) else self.pool
        return vals.shape[1]

    def gather(self, dtype):
        """Dense (S, C·bs, nh, hd) view of the table-selected blocks —
        the XLA fallback read path (one layer at a time inside the layer
        scan, so the transient is 1/L of the all-layer view; a Pallas
        kernel walking the table in-kernel replaces this on TPU).
        Gather FIRST, then dequantize: only the S·C selected blocks pay
        the int8→fp convert, never the whole pool."""
        picked = jax.tree.map(lambda p: p[self.table], self.pool)
        g = dequantize_cache(picked, dtype)        # (S, C, bs, nh, hd)
        return g.reshape((g.shape[0], g.shape[1] * g.shape[2])
                         + g.shape[3:])

    def write(self, chunk, t):
        """Write a (S, kq, …) chunk at per-row positions [t, t+kq) through
        the table (column lookup clamped; pre-zeroed inactive rows land in
        trash)."""
        if isinstance(self.pool, tuple):
            vals, scales = self.pool
            q, s = quantize_kv(chunk)
            return PagedKV((PagedKV(vals, self.table).write(q, t).pool,
                            PagedKV(scales, self.table).write(s, t).pool),
                           self.table)
        bs = self.block_size
        t_arr = jnp.asarray(t)
        B, kq = chunk.shape[:2]
        if t_arr.ndim == 0:
            t_arr = jnp.broadcast_to(t_arr, (B,))
        rows = jnp.arange(B)[:, None]
        slots = t_arr[:, None] + jnp.arange(kq)[None, :]   # (S, kq)
        col = jnp.minimum(slots // bs, self.table.shape[1] - 1)
        pb = self.table[rows, col]
        off = slots % bs
        pool = self.pool.at[pb, off].set(chunk.astype(self.pool.dtype))
        return PagedKV(pool, self.table)


def ragged_attention(q_rows, pool_k, pool_v, table, row_seq, row_pos,
                     pad_lens=None):
    """Attention for a flattened ragged pack of rows over ONE layer's block
    pools (the mixed prefill+decode serving step): q_rows (T, nh, hd),
    pools (NB+1, bs, nh, hd) — int8 ``(values, scales)`` pairs included —
    table (S, C), row_seq/row_pos (T,) per-row metadata (see
    ops/ragged_paged_attention.ragged_rows), pad_lens (S,).

    Dispatches between the Pallas in-kernel table walk (TPU, or interpret
    mode for CPU CI — the ops/fused.py flag convention shared with
    cached_attention's paged arm) and the XLA gather fallback; int8 pools
    take the kernel too (dequant is fused in-kernel)."""
    from ..core.flags import flag
    from ..ops.ragged_paged_attention import (ragged_attention_ref,
                                              ragged_attention_rows)
    interp = (bool(flag("FLAGS_paged_attn_interpret"))
              and jax.default_backend() != "tpu")
    use = flag("FLAGS_use_pallas_kernels") and \
        (jax.default_backend() == "tpu" or interp)
    if use:
        return ragged_attention_rows(q_rows, pool_k, pool_v, table,
                                     row_seq, row_pos, pad_lens,
                                     interpret=interp)
    return ragged_attention_ref(q_rows, pool_k, pool_v, table, row_seq,
                                row_pos, pad_lens)


def ragged_write(pool, chunk, table, row_seq, row_pos):
    """Scatter a flattened ragged chunk (T, nh, hd) into ONE layer's block
    pool at each row's (table-mapped block, offset); padding rows
    (row_pos < 0) land in the trash block.  int8 pools quantize the chunk
    and write both planes (quantize_kv layout)."""
    if isinstance(pool, tuple):
        vals, scales = pool
        q, s = quantize_kv(chunk)
        return (ragged_write(vals, q, table, row_seq, row_pos),
                ragged_write(scales, s, table, row_seq, row_pos))
    bs = pool.shape[1]
    seq = jnp.clip(row_seq, 0, table.shape[0] - 1)
    col = jnp.clip(row_pos // bs, 0, table.shape[1] - 1)
    pb = jnp.where(row_pos >= 0, table[seq, col], 0)
    off = jnp.where(row_pos >= 0, row_pos % bs, 0)
    return pool.at[pb, off].set(chunk.astype(pool.dtype))


def write_cache(cache, chunk, t):
    """Write a (B, kq, nh, hd) k/v chunk into the cache at slots [t, t+kq):
    scalar ``t`` → one dynamic_update_slice; per-row (B,) ``t`` → scatter
    (batched speculative decoding, rows at different positions).

    ``cache`` may be a quantized pair ``(values_int8, scales)`` (see
    ``quantize_kv``) — the chunk is quantized and both planes written —
    or a ``PagedKV`` (block-pool writes through the slot table)."""
    if isinstance(cache, PagedKV):
        return cache.write(chunk, t)
    if isinstance(cache, tuple):
        vals, scales = cache
        q, s = quantize_kv(chunk)
        return (write_cache(vals, q, t), write_cache(scales, s, t))
    t_arr = jnp.asarray(t)
    if t_arr.ndim == 0:
        # rank-generic: the int8 scale plane is (B, T, nh), one rank short
        # of the (B, T, nh, hd) value plane
        return jax.lax.dynamic_update_slice(
            cache, chunk.astype(cache.dtype),
            (0, t_arr) + (0,) * (cache.ndim - 2))
    B, kq = chunk.shape[:2]
    rows = jnp.arange(B)[:, None]
    slots = t_arr[:, None] + jnp.arange(kq)[None, :]
    return cache.at[rows, slots].set(chunk.astype(cache.dtype))


def quantize_kv(x):
    """Symmetric int8 quantization of a k/v tensor over its LAST axis (one
    scale per (…, head, position) vector): HBM traffic for the decode-loop
    cache reads — the serving bottleneck — drops to half of bf16.

    Beyond this reference snapshot (its decode cache is fp only —
    MultiHeadAttention.Cache, python/paddle/nn/layer/transformer.py:151;
    int8 cache-KV serving arrives in the later-Paddle ecosystem's
    fused_multi_transformer path).  TPU-shape: the scale plane rides NEXT
    TO the int8 plane and dequantization fuses into the attention einsum's
    operand read, so no fp copy of the cache ever materializes."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=False)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_cache(cache, dtype):
    """(values_int8, scales) → dense ``dtype`` array; plain arrays pass
    through (so attention call sites stay cache-format agnostic).
    ``PagedKV`` defers to attention time (cached_attention gathers —
    or a Pallas kernel reads the pool directly)."""
    if isinstance(cache, PagedKV):
        return cache
    if isinstance(cache, tuple):
        vals, scales = cache
        return (vals.astype(jnp.float32) * scales[..., None]).astype(dtype)
    return cache


def filter_logits(logits32, temperature, top_k, top_p):
    """The temperature → top-k → nucleus (top-p) filtering pipeline on the
    last axis of an (..., V) fp32 logits array (position-generic: used for
    the single decode position and for speculative verify chunks)."""
    logits32 = logits32 / jnp.asarray(max(temperature, 1e-6), jnp.float32)
    if top_k is not None:
        vals, _ = jax.lax.top_k(logits32, top_k)
        logits32 = jnp.where(logits32 < vals[..., -1:], -jnp.inf, logits32)
    if top_p is not None:
        # nucleus: keep the smallest prefix of the sorted vocab with
        # cumulative probability ≥ top_p (the boundary token stays)
        srt = jnp.flip(jnp.sort(logits32, -1), -1)
        cdf = jnp.cumsum(jax.nn.softmax(srt, -1), -1)
        n_keep = jnp.sum(cdf < top_p, -1) + 1
        kth = jnp.take_along_axis(srt, (n_keep - 1)[..., None], -1)
        logits32 = jnp.where(logits32 < kth, -jnp.inf, logits32)
    return logits32


def apply_repetition_penalty(logits32, presence, penalty):
    """Reference generation_utils / HF RepetitionPenaltyLogitsProcessor
    semantics: for every token already seen in the row (prompt + generated,
    tracked in the (B, V) ``presence`` mask), positive logits divide by the
    penalty and negative logits multiply — both push the token down for
    penalty > 1.  ``penalty`` may be a scalar or a per-row (B,) vector
    (the serving engine's per-request planes); 1.0 is an exact no-op."""
    penalty = jnp.asarray(penalty)
    if penalty.ndim == 1:
        penalty = penalty[:, None]
    pen = jnp.where(logits32 > 0, logits32 / penalty, logits32 * penalty)
    return jnp.where(presence, pen, logits32)


def seed_presence(ids, vocab_size, pad_lens=None):
    """(B, P) prompt ids → (B, V) bool presence plane for the repetition
    penalty, pad positions excluded — ONE copy of the seeding invariant,
    shared by generate() and the serving engine's admission prefill."""
    B, P = ids.shape
    valid = (jnp.ones_like(ids, dtype=bool) if pad_lens is None else
             jnp.arange(P)[None, :] >= pad_lens[:, None])
    return jnp.zeros((B, vocab_size), bool).at[
        jnp.arange(B)[:, None], ids].max(valid)


def suppress_eos(logits32, eos_token_id, suppress):
    """Mask the EOS column with -inf while ``suppress`` — scalar bool (one
    window for the whole batch) or (B,) bool (per-row windows, the serving
    engine's case).  The min_new_tokens contract (HF
    MinNewTokensLengthLogitsProcessor)."""
    col = jnp.arange(logits32.shape[-1]) == eos_token_id
    sup = jnp.asarray(suppress)
    if sup.ndim == 0:
        sup = sup[None]
    return jnp.where(sup[:, None] & col[None, :], -jnp.inf, logits32)


def filter_logits_rows(logits32, temperature, top_k, top_p):
    """``filter_logits`` with PER-ROW parameters as traced data — the
    serving engine's per-request sampling planes (one compiled program for
    any mix of configs; row params are operands, not constants).

    (B, V) fp32 logits; temperature/top_p (B,) fp32, top_k (B,) int32.
    Disabled encodings are exact no-ops: top_k <= 0 or > V keeps every
    token; top_p >= 2.0 is the None encoding (cdf < 2 always holds, so the
    cut sits at the global minimum and nothing is masked)."""
    l = logits32 / jnp.maximum(temperature, 1e-6)[:, None]
    V = l.shape[-1]
    srt = jnp.flip(jnp.sort(l, -1), -1)
    k = jnp.where((top_k <= 0) | (top_k > V), V, top_k)
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], -1)
    l = jnp.where(l < kth, -jnp.inf, l)
    # nucleus on the (possibly top-k-masked) logits, same order as
    # filter_logits: keep the smallest sorted prefix with cdf >= top_p.
    # No second sort needed — masking only floors values strictly below
    # kth to -inf, which preserves srt's descending order
    srt2 = jnp.where(srt < kth, -jnp.inf, srt)
    cdf = jnp.cumsum(jax.nn.softmax(srt2, -1), -1)
    n_keep = jnp.sum(cdf < top_p[:, None], -1) + 1
    kth2 = jnp.take_along_axis(srt2, (jnp.minimum(n_keep, V) - 1)[:, None],
                               -1)
    return jnp.where(l < kth2, -jnp.inf, l)


def make_row_sampler():
    """Per-row sampler over the per-request planes: greedy rows argmax,
    sampling rows draw categorically from the row-filtered logits —
    one program serves any mixture."""
    def sample(logits32, key, temperature, top_k, top_p, greedy):
        l = filter_logits_rows(logits32[:, -1, :], temperature, top_k,
                               top_p)
        return jnp.where(greedy, jnp.argmax(l, -1),
                         jax.random.categorical(key, l, -1)
                         ).astype(jnp.int32)
    return sample


def suppress_eos_rows(logits32, eos_ids, suppress):
    """Per-row EOS suppression for per-request windows: ``eos_ids`` (B,)
    int32 with -1 = this row has no EOS; ``suppress`` (B,) bool."""
    col = jnp.arange(logits32.shape[-1])[None, :] == eos_ids[:, None]
    return jnp.where(col & suppress[:, None], -jnp.inf, logits32)


def make_token_sampler(temperature, top_k, top_p, greedy):
    """Shared last-position sampler for the decode loops (GPT + ERNIE-MoE):
    the filter_logits pipeline then argmax or categorical.  ``logits32`` is
    (B, 1, V) fp32."""
    def sample(logits32, key):
        logits32 = filter_logits(logits32[:, -1, :], temperature, top_k,
                                 top_p)
        if greedy:
            return jnp.argmax(logits32, -1).astype(jnp.int32)
        return jax.random.categorical(key, logits32, -1).astype(jnp.int32)
    return sample


def greedy_verify(d, tpred, active=None):
    """THE greedy speculative-acceptance contract, shared by
    ``generate_speculative`` and the ragged serving engine's fused
    draft+verify step so the semantics cannot drift: accept the longest
    prefix of the draft proposals ``d`` (B, K) that matches the target's
    argmax predictions ``tpred`` (B, K+1) position for position, and
    emit the target's own prediction at the first mismatch (or the bonus
    position when everything matched) — by construction the emitted
    stream equals plain greedy decode token for token.

    ``active`` (B,) bool optionally masks rows whose proposals are
    garbage (a mixed spec/non-spec batch): masked rows get ``lead`` 0,
    so their emitted token is simply ``tpred[:, 0]`` — plain greedy
    decode through the same code path.

    Returns ``(lead, block)``: per-row accepted counts and the (B, K+1)
    token block whose first ``lead + 1`` entries are the round's emitted
    tokens (``d_0..d_{lead-1}``, then the replacement at ``lead``)."""
    B, K = d.shape
    lead = jnp.sum(jnp.cumprod(
        (d == tpred[:, :K]).astype(jnp.int32), axis=1), axis=1)
    if active is not None:
        lead = jnp.where(active, lead, 0)
    repl = jnp.take_along_axis(
        tpred, jnp.minimum(lead, K)[:, None], 1)[:, 0]
    block = jnp.concatenate([d, jnp.zeros((B, 1), jnp.int32)], axis=1)
    block = block.at[jnp.arange(B), lead].set(repl)
    return lead, block


def speculative_accept(q_probs, p_probs, d_tokens, key):
    """Leviathan/Chen acceptance-rejection for one speculative round — the
    output token sequence is distributed EXACTLY as autoregressive sampling
    from the target distributions ``p`` (the lossless-in-distribution
    guarantee; tests/test_generate.py checks the marginal empirically).

    q_probs (B, K, V): draft distributions the K proposed tokens were drawn
    from; p_probs (B, K+1, V): target distributions at the same positions
    plus the bonus position; d_tokens (B, K): the draft proposals.

    Returns (lead (B,), repl (B,)): per row, the count of accepted draft
    tokens and the replacement token for position ``lead`` — drawn from the
    residual distribution norm(max(p - q, 0)) on rejection, or from the
    bonus target distribution when every proposal was accepted.
    """
    B, K, V = q_probs.shape
    key_u, key_r = jax.random.split(key)
    u = jax.random.uniform(key_u, (B, K))
    qd = jnp.take_along_axis(q_probs, d_tokens[..., None], -1)[..., 0]
    pd = jnp.take_along_axis(p_probs[:, :K], d_tokens[..., None], -1)[..., 0]
    accept = u * qd < pd                  # u < p/q without dividing by 0
    lead = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # residual distribution at the first rejected position (bonus p when
    # lead == K); gather per-row with a clamped index then overwrite
    idx = jnp.minimum(lead, K - 1)
    p_at = jnp.take_along_axis(p_probs, idx[:, None, None]
                               .repeat(V, -1), 1)[:, 0]          # (B, V)
    q_at = jnp.take_along_axis(q_probs, idx[:, None, None]
                               .repeat(V, -1), 1)[:, 0]
    resid = jnp.maximum(p_at - q_at, 0.0)
    resid = resid / jnp.maximum(jnp.sum(resid, -1, keepdims=True), 1e-20)
    dist = jnp.where((lead == K)[:, None], p_probs[:, K], resid)
    repl = jax.random.categorical(
        key_r, jnp.log(jnp.maximum(dist, 1e-20)), -1).astype(jnp.int32)
    return lead, repl


def validate_sampler_args(vocab_size, top_k, top_p, greedy, key):
    """Common generate() argument validation (fail before tracing)."""
    if not greedy and key is None:
        raise ValueError("sampling (greedy=False) requires key")
    if top_k is not None and not 1 <= int(top_k) <= vocab_size:
        raise ValueError(f"top_k must be in [1, vocab_size={vocab_size}], "
                         f"got {top_k}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")



class CausalDecoderMixin:
    """KV-cache generation shared by the causal LMs (GPT, ERNIE-MoE).

    ≙ the reference snapshot's MultiHeadAttention.Cache/gen_cache
    incremental decode (python/paddle/nn/layer/transformer.py:151) driven
    by dynamic_decode (python/paddle/nn/decode.py).  TPU-native shape: the cache is a
    STATIC (num_layers, B, max_len, nh, hd) buffer written with
    dynamic_update_slice, the decode loop is one lax.scan — a single XLA
    program regardless of how many tokens are generated, memoized per
    signature.

    Host-class contract: ``self.config`` (vocab_size, compute_dtype,
    max_position_embeddings, num_layers, num_attention_heads, hidden_size),
    ``prefill(params, ids, max_len) -> (h, caches)``,
    ``decode_step(params, h, caches, t) -> (h, caches)``,
    ``decode_logits(params, h) -> fp32 (B, 1, V)``, and wte/wpe param keys.
    """

    def _prefill_embed(self, params, input_ids, pad_lens):
        """Embed a (left-padded) prompt: positions shift by the per-row pad
        length so real tokens get logical positions 0..n-1."""
        dt = jnp.dtype(self.config.compute_dtype)
        P = input_ids.shape[1]
        pos = jnp.maximum(jnp.arange(P)[None, :] - pad_lens[:, None], 0)
        h = jnp.take(params["wte"], input_ids, axis=0) \
            + jnp.take(params["wpe"], pos, axis=0)
        return h.astype(dt)

    @staticmethod
    def _prefill_key_mask(P, pad_lens):
        """Additive key mask for a left-padded prompt: finite -1e30 on pad
        columns (all-pad causal rows then produce garbage-but-finite values
        that nothing reads, instead of NaNs)."""
        return jnp.where(jnp.arange(P)[None, :] < pad_lens[:, None],
                         -1e30, 0.0).astype(jnp.float32)

    @staticmethod
    def _validate_prompt_mask(prompt_mask, input_ids):
        """Eager checks (mask is a host array at generate() time): shape
        match, LEFT padding only (per-row nondecreasing, last column real),
        at least one real token per row."""
        import numpy as _np
        m = _np.asarray(prompt_mask)
        if m.shape != tuple(input_ids.shape):
            raise ValueError(f"prompt_mask shape {m.shape} != input_ids "
                             f"shape {tuple(input_ids.shape)}")
        if not _np.isin(m, (0, 1)).all():
            raise ValueError("prompt_mask must be 0/1")
        if (m.sum(axis=1) == 0).any():
            raise ValueError("prompt_mask has an all-padding row")
        if (_np.diff(m.astype(_np.int8), axis=1) < 0).any() or \
                not m[:, -1].all():
            raise ValueError(
                "prompt_mask must be LEFT-padded (zeros then ones; the last "
                "position must be a real token) — right-padded masks would "
                "silently generate from a pad position")

    def _embed_one(self, params, tok, t, pad_lens=None):
        """Embed one token per row at cache slot ``t`` (scalar or per-row
        (B,)): (B,) -> (B, 1, H).  With left-padded prompts the LOGICAL
        position is t - pad_lens[b]."""
        dt = jnp.dtype(self.config.compute_dtype)
        wte = jnp.take(params["wte"], tok[:, None], axis=0)
        t_arr = jnp.asarray(t)
        if pad_lens is not None:
            wpe = params["wpe"][t_arr - pad_lens][:, None, :]
        elif t_arr.ndim == 0:
            wpe = params["wpe"][t_arr][None, None, :]
        else:
            wpe = params["wpe"][t_arr][:, None, :]
        return (wte + wpe).astype(dt)

    def init_cache(self, batch_size: int, max_len: int):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        nh = c.num_attention_heads
        hd = c.hidden_size // nh
        shape = (c.num_layers, batch_size, max_len, nh, hd)
        if getattr(c, "kv_cache_dtype", None) == "int8":
            def one():
                return (jnp.zeros(shape, jnp.int8),
                        jnp.zeros(shape[:-1], jnp.float32))
            return one(), one()
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def generate(self, params, input_ids, max_new_tokens: int,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, greedy: bool = True, key=None,
                 prompt_mask=None, repetition_penalty: float = 1.0,
                 min_new_tokens: int = 0, eos_token_id: Optional[int] = None):
        """Autoregressive generation with a static KV cache.

        input_ids (B, P) int32; returns (B, max_new_tokens) generated ids.
        greedy=True → argmax decoding; else temperature (+ optional top-k
        and/or nucleus top-p) sampling with ``key``.  The whole decode loop
        is ONE compiled program per (P, max_new_tokens, temperature, top_k,
        top_p, greedy) signature, memoized on the model — vary only the
        prompt content (and bucket P via paddle.jit.bucketize) for serving
        cache hits.

        ``prompt_mask`` (B, P), 1 = real token, 0 = padding: prompts must be
        LEFT-padded (real tokens at the end, so the last position is always
        real).  Pad positions are excluded from attention and position ids
        shift by the per-row pad length — pad lengths are traced data, so
        ragged batches share one compiled program per bucket.

        ``repetition_penalty`` > 1 pushes already-seen tokens (prompt +
        generated) down (reference generation_utils semantics);
        ``min_new_tokens`` masks ``eos_token_id`` for the first n emissions.
        """
        c = self.config
        B, P = input_ids.shape
        if max_new_tokens <= 0:
            return jnp.zeros((B, 0), jnp.int32)
        max_len = P + max_new_tokens
        if max_len > c.max_position_embeddings:
            raise ValueError(f"P + max_new_tokens = {max_len} exceeds "
                             f"max_position_embeddings ({c.max_position_embeddings})")
        validate_sampler_args(c.vocab_size, top_k, top_p, greedy, key)
        if repetition_penalty <= 0:
            raise ValueError("repetition_penalty must be > 0")
        if min_new_tokens > 0 and eos_token_id is None:
            raise ValueError("min_new_tokens needs eos_token_id (it works "
                             "by suppressing EOS)")
        if eos_token_id is not None and not 0 <= eos_token_id < c.vocab_size:
            raise ValueError(f"eos_token_id {eos_token_id} outside vocab "
                             f"(size {c.vocab_size}) — suppression would be "
                             f"a silent no-op")
        key = jax.random.key(0) if key is None else key
        run = self._gen_program(P, max_new_tokens, float(temperature),
                                None if top_k is None else int(top_k),
                                None if top_p is None else float(top_p),
                                greedy, masked=prompt_mask is not None,
                                repetition_penalty=float(repetition_penalty),
                                min_new_tokens=int(min_new_tokens),
                                # eos only shapes the program when it
                                # suppresses; don't fragment the jit cache
                                # (and recompile) per tokenizer eos id
                                eos_token_id=(eos_token_id
                                              if min_new_tokens > 0
                                              else None))
        if prompt_mask is None:
            return run(params, jnp.asarray(input_ids), key)
        self._validate_prompt_mask(prompt_mask, input_ids)
        pad_lens = (P - jnp.sum(jnp.asarray(prompt_mask, jnp.int32), axis=1)) \
            .astype(jnp.int32)
        return run(params, jnp.asarray(input_ids), key, pad_lens)

    def _gen_program(self, P, max_new_tokens, temperature, top_k, top_p,
                     greedy, masked=False, repetition_penalty=1.0,
                     min_new_tokens=0, eos_token_id=None):
        """Build (and memoize) the jitted prefill+decode program for one
        (P, max_new_tokens, temperature, top_k, top_p, greedy, processors)
        signature — repeated generate() calls with the same signature hit
        the jit cache instead of recompiling the whole model."""
        cache_key = (P, max_new_tokens, temperature, top_k, top_p, greedy,
                     masked, repetition_penalty, min_new_tokens, eos_token_id)
        progs = self.__dict__.setdefault("_gen_programs", {})
        if cache_key in progs:
            return progs[cache_key]
        max_len = P + max_new_tokens
        sample = make_token_sampler(temperature, top_k, top_p, greedy)
        V = self.config.vocab_size
        track = repetition_penalty != 1.0  # presence mask only when needed

        def process(logits32, presence, n_emitted):
            """(B, 1, V) logits through the pre-filter processors."""
            l2 = logits32[:, -1, :]
            if track:
                l2 = apply_repetition_penalty(l2, presence,
                                              repetition_penalty)
            if min_new_tokens > 0:
                l2 = suppress_eos(l2, eos_token_id,
                                  n_emitted < min_new_tokens)
            return l2[:, None, :]

        @jax.jit
        def run(params, input_ids, key, pad_lens=None):
            B = input_ids.shape[0]
            presence = seed_presence(input_ids, V, pad_lens) if track \
                else None
            h, caches = self.prefill(params, input_ids, max_len,
                                     pad_lens=pad_lens)
            key, k0 = jax.random.split(key)
            tok0 = sample(process(self.decode_logits(params, h[:, -1:]),
                                  presence, 0), k0)
            if track:
                presence = presence.at[jnp.arange(B), tok0].set(True)

            def body(carry, i):
                tok, caches, key, presence = carry
                t = P + i  # this token's slot in the cache
                h = self._embed_one(params, tok, t, pad_lens=pad_lens)
                h, caches = self.decode_step(params, h, caches, t,
                                             pad_lens=pad_lens)
                key, sub = jax.random.split(key)
                ntok = sample(process(self.decode_logits(params, h),
                                      presence, i + 1), sub)
                if track:
                    presence = presence.at[jnp.arange(B), ntok].set(True)
                return (ntok, caches, key, presence), ntok

            (last, _, _, _), toks = jax.lax.scan(
                body, (tok0, caches, key, presence),
                jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([tok0[:, None], toks.T], axis=1)

        progs[cache_key] = run
        return run

    def _embed_chunk(self, params, toks, t0, pad_lens=None):
        """Embed a token chunk at cache slots [t0, t0+k).

        toks (k,) with scalar t0 → (1, k, H); toks (B, k) with t0 (B,) →
        (B, k, H) (per-row slots — batched speculative decoding).  With
        left-padded prompts (``pad_lens``) logical positions shift by the
        per-row pad length, matching _embed_one/_prefill_embed."""
        dt = jnp.dtype(self.config.compute_dtype)
        if toks.ndim == 1:
            k = toks.shape[0]
            pos = t0 + jnp.arange(k)
            if pad_lens is not None:
                pos = jnp.maximum(pos - pad_lens[0], 0)
            return (jnp.take(params["wte"], toks, axis=0)[None]
                    + params["wpe"][pos][None]).astype(dt)
        B, k = toks.shape
        pos = jnp.asarray(t0)[:, None] + jnp.arange(k)[None, :]   # (B, k)
        if pad_lens is not None:
            pos = jnp.maximum(pos - pad_lens[:, None], 0)
        return (jnp.take(params["wte"], toks, axis=0)
                + jnp.take(params["wpe"], pos, axis=0)).astype(dt)

    def _embed_ragged(self, params, toks, row_seq, row_pos, pad_lens):
        """Embed a flattened ragged pack: toks (T,) one token per row,
        row_seq (T,) owning sequence, row_pos (T,) kv position (-1 for
        padding rows), pad_lens (S,) per-sequence left-pad lengths.
        Logical positions shift by the owning sequence's pad (the
        _embed_one/_embed_chunk convention); returns (1, T, H)."""
        dt = jnp.dtype(self.config.compute_dtype)
        seq = jnp.clip(row_seq, 0, pad_lens.shape[0] - 1)
        pos = jnp.clip(row_pos - pad_lens[seq], 0,
                       params["wpe"].shape[0] - 1)
        h = jnp.take(params["wte"], toks, axis=0) + params["wpe"][pos]
        return h[None].astype(dt)

    def generate_speculative(self, params, input_ids, max_new_tokens: int,
                             draft_model, draft_params, draft_k: int = 4,
                             greedy: bool = True, temperature: float = 1.0,
                             top_k: Optional[int] = None,
                             top_p: Optional[float] = None, key=None,
                             return_rounds: bool = False):
        """Speculative decoding (≙ the draft-and-verify serving
        optimization; LOSSLESS — greedy mode is bit-identical to this
        model's greedy ``generate``, and sampling mode draws from EXACTLY
        the target's filtered distribution via Leviathan/Chen
        acceptance-rejection, `speculative_accept`).

        Per round: the draft proposes ``draft_k`` tokens one at a time
        (argmax in greedy mode, sampled from its filtered distribution in
        sampling mode); the target verifies all of them (plus one bonus
        token) in ONE chunked cache step (cached_attention's k-query form).
        The accepted prefix + a correction/resample are kept, so each round
        emits 1..draft_k+1 tokens at the cost of one target chunk — the
        speedup is the draft's acceptance rate.  The draft cache is then
        re-ingested from the same verify chunk (its sequential loop never
        fed the last proposal, which would leave a permanent zero-kv hole
        after a fully-accepted round); stale slots from rejected tokens are
        always rewritten as the next round's input before anything reads
        them.

        Batched: rows accept independently (per-row cache slots via the
        vectorized write/attention offsets); finished rows keep writing
        into the buffer's slack region until the slowest row completes.
        The draft must share the vocabulary.  In sampling mode both models
        apply the SAME temperature/top-k/top-p filter; the draft proposes
        from its filtered distribution and rejections resample from the
        residual norm(max(p - q, 0)).
        """
        c = self.config
        B, P = input_ids.shape
        if draft_model.config.vocab_size != c.vocab_size:
            raise ValueError(
                f"draft vocab ({draft_model.config.vocab_size}) != target "
                f"vocab ({c.vocab_size}) — speculative acceptance compares "
                f"token ids")
        if max_new_tokens <= 0:
            return jnp.zeros((B, 0), jnp.int32)
        K = int(draft_k)
        if K < 1:
            raise ValueError(f"draft_k must be >= 1, got {draft_k}")
        need = P + max_new_tokens + K
        for m, who in ((c, "target"), (draft_model.config, "draft")):
            if need > m.max_position_embeddings:
                raise ValueError(
                    f"P + max_new_tokens + draft_k = {need} exceeds the "
                    f"{who}'s max_position_embeddings "
                    f"({m.max_position_embeddings})")
        validate_sampler_args(c.vocab_size, top_k, top_p, greedy, key)
        key = jax.random.key(0) if key is None else key
        run = self._spec_program(
            draft_model, P, max_new_tokens, K, greedy, float(temperature),
            None if top_k is None else int(top_k),
            None if top_p is None else float(top_p))
        toks, rounds = run(params, draft_params, jnp.asarray(input_ids), key)
        return (toks, rounds) if return_rounds else toks

    def _spec_program(self, draft_model, P, max_new_tokens, K, greedy,
                      temperature, top_k, top_p):
        # keyed by the draft's config signature with a weakref identity
        # check: one entry per signature (bounded memory — a fresh draft
        # instance replaces, never accumulates), and a recycled id() can
        # never alias a dead draft
        import weakref
        dcfg = draft_model.config
        cache_key = ("spec", type(draft_model).__name__, dcfg.vocab_size,
                     dcfg.num_layers, dcfg.hidden_size, P, max_new_tokens, K,
                     greedy, temperature, top_k, top_p)
        progs = self.__dict__.setdefault("_gen_programs", {})
        entry = progs.get(cache_key)
        if entry is not None:
            ref, cached_run = entry
            if ref() is draft_model:
                return cached_run
        N = max_new_tokens
        buf_len = P + N + K + 1  # slack: a round may write past P+N-1
        max_len = buf_len

        def filt(logits):
            return filter_logits(logits.astype(jnp.float32), temperature,
                                 top_k, top_p)

        sample0 = make_token_sampler(temperature, top_k, top_p, greedy)

        @jax.jit
        def run(params, dparams, ids, key):
            B = ids.shape[0]
            rows = jnp.arange(B)
            h, tc = self.prefill(params, ids, max_len)
            _, dc = draft_model.prefill(dparams, ids, max_len)
            key, k0 = jax.random.split(key)
            tok0 = sample0(self.decode_logits(params, h[:, -1:]), k0)  # (B,)
            buf = jnp.zeros((B, buf_len), jnp.int32) \
                .at[:, :P].set(ids.astype(jnp.int32))
            buf = buf.at[:, P].set(tok0)

            def cond(st):
                return jnp.any(st[1] < P + N)

            # B == 1 keeps the scalar slot index: dynamic_update_slice /
            # dynamic_slice instead of scatter/gather on the latency path
            def slot(t_vec):
                return t_vec if B > 1 else t_vec[0]

            def body(st):
                buf, n, tc, dc, key, rounds = st                # n (B,)
                prev = buf[rows, n - 1]                         # (B,)
                key, kd, ka = jax.random.split(key, 3)

                def dstep(carry, i):
                    tok, dc = carry
                    hh = draft_model._embed_one(dparams, tok, slot(n - 1 + i))
                    hh, dc = draft_model.decode_step(dparams, hh, dc,
                                                     slot(n - 1 + i))
                    ql = filt(draft_model.decode_logits(dparams, hh)[:, -1])
                    if greedy:
                        ntok = jnp.argmax(ql, -1).astype(jnp.int32)
                        qout = jnp.zeros((ql.shape[0], 0))  # probs unused
                    else:
                        ntok = jax.random.categorical(
                            jax.random.fold_in(kd, i), ql, -1) \
                            .astype(jnp.int32)
                        qout = jax.nn.softmax(ql, -1)
                    return (ntok, dc), (ntok, qout)

                (_, dc), (d, qp) = jax.lax.scan(dstep, (prev, dc),
                                                jnp.arange(K))
                d = d.T                                         # (B, K)

                # verify: ONE target chunk over [prev, d_0..d_{K-1}] gives
                # the target's filtered logits for positions n..n+K
                inp = jnp.concatenate([prev[:, None], d], axis=1)  # (B, K+1)
                hin = self._embed_chunk(params, inp[0] if B == 1 else inp,
                                        slot(n - 1))
                hv, tc = self.decode_step(params, hin, tc, slot(n - 1))
                tl = filt(self.decode_logits(params, hv))       # (B, K+1, V)
                # re-ingest the chunk into the DRAFT cache: the sequential
                # draft loop never fed d_{K-1}, so slot n+K-1 would stay a
                # zero-kv hole after a fully-accepted round (permanently
                # degrading acceptance; outputs stay correct so only a
                # round-count test can see it)
                dh = draft_model._embed_chunk(dparams,
                                              inp[0] if B == 1 else inp,
                                              slot(n - 1))
                _, dc = draft_model.decode_step(dparams, dh, dc, slot(n - 1))
                if greedy:
                    # ONE copy of the greedy acceptance rule (greedy_verify)
                    # shared with the ragged serving engine's fused
                    # draft+verify step; only the first lead+1 entries of
                    # the block are ever read (rows advance by lead + 1)
                    tpred = jnp.argmax(tl, -1).astype(jnp.int32)
                    lead, cand = greedy_verify(d, tpred)
                else:
                    q_probs = jnp.swapaxes(qp, 0, 1)            # (B, K, V)
                    p_probs = jax.nn.softmax(tl, -1)            # (B, K+1, V)
                    lead, repl = speculative_accept(q_probs, p_probs, d, ka)
                    d_ext = jnp.concatenate(
                        [d, jnp.zeros((B, 1), jnp.int32)], axis=1)
                    cand = jnp.where(
                        jnp.arange(K + 1)[None] < lead[:, None],
                        d_ext, repl[:, None])
                slots = n[:, None] + jnp.arange(K + 1)[None]
                buf = buf.at[rows[:, None], slots].set(cand)
                n = jnp.minimum(n + lead + 1, P + N)
                return (buf, n, tc, dc, key, rounds + 1)

            n0 = jnp.full((B,), P + 1)
            buf, n, tc, dc, key, rounds = jax.lax.while_loop(
                cond, body, (buf, n0, tc, dc, key, jnp.zeros((), jnp.int32)))
            return buf[:, P:P + N], rounds

        progs[cache_key] = (weakref.ref(draft_model), run)
        return run

    def generate_beam(self, params, input_ids, max_new_tokens: int,
                      num_beams: int = 4, length_penalty: float = 1.0,
                      eos_token_id: Optional[int] = None):
        """Beam-search decoding on the KV cache (≙ generation_utils
        BeamSearchScorer semantics, fixed length budget).

        Returns (sequences (B, max_new_tokens), scores (B,)) for the best
        beam per batch row; ``scores`` are summed log-probs divided by
        length**length_penalty.  ``eos_token_id``: beams that emit EOS are
        frozen (EOS repeats, log-prob stops accumulating) so shorter
        hypotheses compete under the penalty.

        TPU shape: beams fold into the batch dim (B*K), the cache reorder is
        one take_along_axis per step, and the whole search is a single
        lax.scan — no dynamic shapes, no host sync inside the loop.
        """
        c = self.config
        B, P = input_ids.shape
        K = int(num_beams)
        if not 1 <= K <= c.vocab_size:
            raise ValueError(f"num_beams must be in [1, vocab_size="
                             f"{c.vocab_size}], got {num_beams}")
        if eos_token_id is not None and not 0 <= eos_token_id < c.vocab_size:
            raise ValueError(f"eos_token_id {eos_token_id} outside the vocab "
                             f"[0, {c.vocab_size}) — EOS freezing would "
                             f"silently never trigger")
        if max_new_tokens <= 0:
            return jnp.zeros((B, 0), jnp.int32), jnp.zeros((B,), jnp.float32)
        max_len = P + max_new_tokens
        if max_len > c.max_position_embeddings:
            raise ValueError(f"P + max_new_tokens = {max_len} exceeds "
                             f"max_position_embeddings ({c.max_position_embeddings})")
        run = self._beam_program(P, max_new_tokens, K, float(length_penalty),
                                 eos_token_id)
        return run(params, jnp.asarray(input_ids))

    def _beam_program(self, P, max_new_tokens, K, length_penalty,
                      eos_token_id):
        cache_key = ("beam", P, max_new_tokens, K, length_penalty,
                     eos_token_id)
        progs = self.__dict__.setdefault("_gen_programs", {})
        if cache_key in progs:
            return progs[cache_key]
        c = self.config
        max_len = P + max_new_tokens
        V = c.vocab_size
        NEG = jnp.float32(-1e30)

        def logprobs_last(params, h):
            return jax.nn.log_softmax(
                self.decode_logits(params, h)[:, -1, :].astype(jnp.float32),
                -1)

        @jax.jit
        def run(params, input_ids):
            B = input_ids.shape[0]
            h, caches = self.prefill(params, input_ids, max_len)
            lp0 = logprobs_last(params, h)                      # (B, V)
            # beams start identical: only beam 0 is live at step 0
            top_lp, top_tok = jax.lax.top_k(lp0, K)             # (B, K)
            cum = top_lp
            if eos_token_id is not None:
                finished0 = top_tok == eos_token_id
            else:
                finished0 = jnp.zeros((B, K), bool)
            # per-beam hypothesis length (tokens incl. EOS): finished beams
            # keep the length at which they finished so the length penalty
            # ranks short hypotheses correctly (BeamSearchScorer semantics)
            lengths0 = jnp.where(finished0, 1.0,
                                 float(max_new_tokens)).astype(jnp.float32)
            # tile caches per beam: (nl, B, ...) -> (nl, B*K, ...)
            caches = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, K, axis=1), caches)

            def body(carry, i):
                tok, caches, cum, finished, lengths = carry
                t = P + i
                hh = self._embed_one(params, tok, t)
                hh, caches = self.decode_step(params, hh, caches, t)
                lp = logprobs_last(params, hh).reshape(B, K, V)
                if eos_token_id is not None:
                    # frozen beams: only EOS continues, at zero cost
                    eos_only = jnp.full((V,), NEG).at[eos_token_id].set(0.0)
                    lp = jnp.where(finished[..., None], eos_only[None, None],
                                   lp)
                total = cum[..., None] + lp                      # (B, K, V)
                flat = total.reshape(B, K * V)
                cum, idx = jax.lax.top_k(flat, K)                # (B, K)
                parent = idx // V
                ntok = (idx % V).astype(jnp.int32)
                if eos_token_id is not None:
                    was = jnp.take_along_axis(finished, parent, axis=1)
                    lengths = jnp.take_along_axis(lengths, parent, axis=1)
                    newly = ~was & (ntok == eos_token_id)
                    # token emitted at body step i is hypothesis token i+2
                    lengths = jnp.where(newly, (i + 2).astype(jnp.float32),
                                        lengths)
                    finished = was | newly
                # reorder caches to the surviving beams
                def reorder(a):
                    nl = a.shape[0]
                    ab = a.reshape((nl, B, K) + a.shape[2:])
                    pidx = parent.reshape((1, B, K) + (1,) * (ab.ndim - 3))
                    return jnp.take_along_axis(ab, pidx, axis=2).reshape(a.shape)
                caches = jax.tree_util.tree_map(reorder, caches)
                tok = ntok.reshape(B * K)
                return (tok, caches, cum, finished, lengths), (ntok, parent)

            (_, _, cum, _, lengths), (toks, parents) = jax.lax.scan(
                body, (top_tok.reshape(B * K), caches, cum, finished0,
                       lengths0),
                jnp.arange(max_new_tokens - 1))

            # backtrace: walk parents from the best final beam to step 0
            scores = cum / jnp.power(lengths, length_penalty)
            best = jnp.argmax(scores, axis=1)                    # (B,)

            def back(k, step):
                st, sp = step                                    # (B,K) each
                tok_t = jnp.take_along_axis(st, k[:, None], 1)[:, 0]
                k = jnp.take_along_axis(sp, k[:, None], 1)[:, 0]
                return k, tok_t

            k_last, toks_rev = jax.lax.scan(
                back, best, (toks[::-1], parents[::-1]))
            first = jnp.take_along_axis(top_tok, k_last[:, None], 1)[:, 0]
            seq = jnp.concatenate([first[:, None], toks_rev[::-1].T], axis=1)
            best_score = jnp.take_along_axis(scores, best[:, None], 1)[:, 0]
            return seq, best_score

        progs[cache_key] = run
        return run




def save_generate_program(model, params, path: str, prompt_len: int,
                          max_new_tokens: int, batch_size: int = 1,
                          temperature: float = 1.0, top_k=None, top_p=None,
                          greedy: bool = True, masked: bool = False,
                          platforms=("cpu", "tpu")):
    """Export one generation program as a self-contained serving artifact.

    ≙ jit.save's ``__model__`` + params layout (save_inference_model), but
    for the full prefill+decode loop: the StableHLO program (jax.export
    bytes) plus pickled weights.  The exported function takes
    (input_ids (B, P) int32, seed uint32[, pad_lens int32 when
    ``masked=True`` — left-padded ragged prompts]) — the PRNG key is built
    inside the program so no key types cross the serialization boundary.
    Lowered for every platform in ``platforms`` so a CPU-built artifact
    serves on TPU.

    Files: path + ".genmodel" (program), path + ".genparams" (weights),
    path + ".genmeta" (shapes/sampler signature).
    """
    import pickle

    import numpy as _np
    from jax import export as jax_export

    # same eager contract as generate(): fail here, not at serve time
    if max_new_tokens <= 0:
        raise ValueError("max_new_tokens must be positive for an exported "
                         "program (an empty program is not a useful artifact)")
    max_len = prompt_len + max_new_tokens
    if max_len > model.config.max_position_embeddings:
        raise ValueError(
            f"prompt_len + max_new_tokens = {max_len} exceeds "
            f"max_position_embeddings ({model.config.max_position_embeddings})")
    validate_sampler_args(model.config.vocab_size, top_k, top_p, greedy,
                          key=object())  # key is generated in-program

    run = model._gen_program(prompt_len, max_new_tokens, float(temperature),
                             None if top_k is None else int(top_k),
                             None if top_p is None else float(top_p), greedy,
                             masked=masked)

    if masked:
        def entry(params, input_ids, seed, pad_lens):
            return run(params, input_ids, jax.random.key(seed), pad_lens)
        extra = [jax.ShapeDtypeStruct((batch_size,), jnp.int32)]
    else:
        def entry(params, input_ids, seed):
            return run(params, input_ids, jax.random.key(seed))
        extra = []

    p_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    exported = jax_export.export(jax.jit(entry), platforms=list(platforms))(
        p_shapes,
        jax.ShapeDtypeStruct((batch_size, prompt_len), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.uint32), *extra)
    with open(path + ".genmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".genparams", "wb") as f:
        pickle.dump(jax.tree_util.tree_map(_np.asarray, params), f)
    with open(path + ".genmeta", "wb") as f:
        pickle.dump({"prompt_len": prompt_len, "batch_size": batch_size,
                     "max_new_tokens": max_new_tokens,
                     "temperature": temperature, "top_k": top_k,
                     "top_p": top_p, "greedy": greedy, "masked": masked,
                     "platforms": tuple(platforms)}, f)


def load_generate_program(path: str):
    """Load a save_generate_program artifact.  Returns (fn, meta) where
    ``fn(input_ids, seed=0[, prompt_mask=...]) -> (B, max_new_tokens)``
    has the weights baked in; ``prompt_mask`` is accepted (and required)
    when the artifact was exported with ``masked=True``."""
    import pickle

    from jax import export as jax_export

    with open(path + ".genmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".genparams", "rb") as f:
        params = pickle.load(f)
    with open(path + ".genmeta", "rb") as f:
        meta = pickle.load(f)

    def fn(input_ids, seed=0, prompt_mask=None):
        ids = jnp.asarray(input_ids, jnp.int32)
        args = [params, ids, jnp.asarray(seed, jnp.uint32)]
        if meta["masked"]:
            if prompt_mask is None:
                raise ValueError("this artifact was exported masked=True; "
                                 "pass prompt_mask")
            CausalDecoderMixin._validate_prompt_mask(prompt_mask, ids)
            args.append((ids.shape[1] - jnp.sum(
                jnp.asarray(prompt_mask, jnp.int32), axis=1)).astype(jnp.int32))
        elif prompt_mask is not None:
            raise ValueError("artifact exported without masked=True cannot "
                             "serve ragged prompts")
        return exported.call(*args)

    return fn, meta
