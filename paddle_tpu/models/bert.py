"""BERT model family (reference capability: BERT-base fused-attention config in
BASELINE.json; fused stack ≙ operators/fused/fused_attention_op.cu +
fused_feedforward_op.cu).

Same TPU-first skeleton as models/gpt.py: all encoder layers stacked in one
pytree consumed by ``lax.scan`` (O(1) compile in depth), flash attention from
paddle_tpu.ops, bf16 compute / fp32 params, TP via dims_mapping annotations.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..nn.layer.base import Layer
from ..ops.attention import dense_attention, flash_attention


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=512, type_vocab_size=2,
                 hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 compute_dtype="bfloat16", use_flash_attention=True,
                 scan_unroll=1, hidden_act="gelu"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.compute_dtype = compute_dtype
        self.use_flash_attention = use_flash_attention
        # "gelu" = exact erf form (paddle F.gelu / HF BERT default);
        # "gelu_approx" = tanh form.  Round-2 shipped the tanh approx
        # unconditionally — a measurable deviation from the reference.
        if hidden_act not in ("gelu", "gelu_approx"):
            raise ValueError(f"hidden_act must be 'gelu' or 'gelu_approx', "
                             f"got {hidden_act!r}")
        self.hidden_act = hidden_act
        self.scan_unroll = scan_unroll


BERT_CONFIGS = {
    "bert-base": dict(hidden_size=768, num_hidden_layers=12, num_attention_heads=12),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16),
}


def bert_preset(name: str, **overrides) -> BertConfig:
    cfg = dict(BERT_CONFIGS[name])
    cfg.update(overrides)
    return BertConfig(**cfg)


class BertModel(Layer):
    """Bidirectional encoder with stacked block parameters."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = c = config
        L, H, V = c.num_hidden_layers, c.hidden_size, c.vocab_size
        I = c.intermediate_size
        std = c.initializer_range

        def normal(shape, s=std):
            from ..nn.initializer import Normal
            return Normal(0.0, s)(shape, "float32")

        def param(name, data, mapping=None):
            p = Parameter(data, name=name)
            if mapping:
                p._dims_mapping = mapping
            self.add_parameter(name.replace(".", "_"), p)
            return p

        zeros, ones = (lambda s: jnp.zeros(s, jnp.float32)), (lambda s: jnp.ones(s, jnp.float32))
        self.word_emb = param("word_emb", normal([V, H]), {0: "model"})
        self.pos_emb = param("pos_emb", normal([c.max_position_embeddings, H]))
        self.type_emb = param("type_emb", normal([c.type_vocab_size, H]))
        self.emb_ln_w = param("emb_ln_w", ones([H]))
        self.emb_ln_b = param("emb_ln_b", zeros([H]))
        # stacked encoder blocks — post-LN (original BERT residual order)
        self.blocks_qkv_w = param("blocks.qkv_w", normal([L, H, 3 * H]), {2: "model"})
        self.blocks_qkv_b = param("blocks.qkv_b", zeros([L, 3 * H]), {1: "model"})
        self.blocks_proj_w = param("blocks.proj_w",
                                   normal([L, H, H], std / math.sqrt(2 * L)),
                                   {1: "model"})
        self.blocks_proj_b = param("blocks.proj_b", zeros([L, H]))
        self.blocks_ln1_w = param("blocks.ln1_w", ones([L, H]))
        self.blocks_ln1_b = param("blocks.ln1_b", zeros([L, H]))
        self.blocks_fc1_w = param("blocks.fc1_w", normal([L, H, I]), {2: "model"})
        self.blocks_fc1_b = param("blocks.fc1_b", zeros([L, I]), {1: "model"})
        self.blocks_fc2_w = param("blocks.fc2_w",
                                  normal([L, I, H], std / math.sqrt(2 * L)),
                                  {1: "model"})
        self.blocks_fc2_b = param("blocks.fc2_b", zeros([L, H]))
        self.blocks_ln2_w = param("blocks.ln2_w", ones([L, H]))
        self.blocks_ln2_b = param("blocks.ln2_b", zeros([L, H]))
        # pooler + heads
        self.pooler_w = param("pooler_w", normal([H, H]))
        self.pooler_b = param("pooler_b", zeros([H]))
        self.mlm_dense_w = param("mlm_dense_w", normal([H, H]))
        self.mlm_dense_b = param("mlm_dense_b", zeros([H]))
        self.mlm_ln_w = param("mlm_ln_w", ones([H]))
        self.mlm_ln_b = param("mlm_ln_b", zeros([H]))
        self.mlm_bias = param("mlm_bias", zeros([V]), {0: "model"})
        self.nsp_w = param("nsp_w", normal([H, 2]))
        self.nsp_b = param("nsp_b", zeros([2]))

    @staticmethod
    def stacked_param_names():
        return [f"blocks_{n}" for n in ("qkv_w", "qkv_b", "proj_w", "proj_b",
                                        "ln1_w", "ln1_b", "fc1_w", "fc1_b",
                                        "fc2_w", "fc2_b", "ln2_w", "ln2_b")]

    # -------------------------------------------------------- pure functions
    def _ln(self, x, w, b):
        eps = self.config.layer_norm_eps
        x32 = x.astype(jnp.float32)
        m = x32.mean(-1, keepdims=True)
        v = x32.var(-1, keepdims=True)
        return (x32 - m) * jax.lax.rsqrt(v + eps) * w + b

    def embed_fn(self, params, input_ids, token_type_ids=None):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        pos = jnp.arange(input_ids.shape[-1])
        h = jnp.take(params["word_emb"], input_ids, axis=0) + params["pos_emb"][pos]
        if token_type_ids is None:
            h = h + params["type_emb"][0]
        else:
            h = h + jnp.take(params["type_emb"], token_type_ids, axis=0)
        return self._ln(h, params["emb_ln_w"], params["emb_ln_b"]).astype(dt)

    def block_fn(self, sl: Dict[str, Any], h, attn_mask=None):
        c = self.config
        dt = h.dtype
        B, Lq, H = h.shape
        nh = c.num_attention_heads
        hd = H // nh
        qkv = h @ sl["blocks_qkv_w"].astype(dt) + sl["blocks_qkv_b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(B, Lq, nh, hd) for t in (q, k, v))
        if c.use_flash_attention:
            # the (B,1,1,L) padding mask rides inside the Pallas kernel as a
            # key mask — no dense fallback (ops/attention.py)
            att = flash_attention(q, k, v, causal=False, key_mask=attn_mask)
        else:
            att = dense_attention(q, k, v, mask=attn_mask, causal=False)
        att = att.reshape(B, Lq, H)
        from ..core.flags import flag as _flag

        def epilogue(x, residual, ln_w, ln_b, bias):
            """LN(residual + x + bias): Pallas fused epilogue (ops/fused.py ≙
            fused_layernorm_residual_dropout_bias.h) when FLAGS_use_fused_ln,
            else the plain _ln path — identical math up to fp32 rounding."""
            if _flag("FLAGS_use_fused_ln"):
                from ..ops.fused import fused_ln_residual_dropout
                return fused_ln_residual_dropout(
                    x, residual, ln_w, ln_b, bias=bias,
                    eps=c.layer_norm_eps)[0].astype(dt)
            return self._ln(residual + x + bias.astype(dt), ln_w, ln_b).astype(dt)

        h = epilogue(att @ sl["blocks_proj_w"].astype(dt), h,
                     sl["blocks_ln1_w"], sl["blocks_ln1_b"],
                     sl["blocks_proj_b"])
        ff = jax.nn.gelu(h @ sl["blocks_fc1_w"].astype(dt)
                         + sl["blocks_fc1_b"].astype(dt),
                         approximate=c.hidden_act == "gelu_approx")
        return epilogue(ff @ sl["blocks_fc2_w"].astype(dt), h,
                        sl["blocks_ln2_w"], sl["blocks_ln2_b"],
                        sl["blocks_fc2_b"])

    def scan_blocks(self, params, h, attn_mask=None, remat=True):
        stacked = {k: params[k] for k in self.stacked_param_names()}
        fn = (jax.checkpoint(lambda sl, hh: self.block_fn(sl, hh, attn_mask))
              if remat else (lambda sl, hh: self.block_fn(sl, hh, attn_mask)))
        from ._scan import resolve_scan_unroll
        out, _ = jax.lax.scan(lambda carry, sl: (fn(sl, carry), None), h, stacked,
                              unroll=resolve_scan_unroll(self.config))
        return out

    def encode(self, params, input_ids, token_type_ids=None, attn_mask=None,
               remat=False):
        h = self.embed_fn(params, input_ids, token_type_ids)
        return self.scan_blocks(params, h, attn_mask, remat=remat)

    def pool_fn(self, params, h):
        dt = h.dtype
        return jnp.tanh(h[:, 0] @ params["pooler_w"].astype(dt)
                        + params["pooler_b"].astype(dt))

    def _mlm_logits(self, params, h):
        dt = h.dtype
        x = jax.nn.gelu(h @ params["mlm_dense_w"].astype(dt)
                        + params["mlm_dense_b"].astype(dt),
                        approximate=self.config.hidden_act == "gelu_approx")
        x = self._ln(x, params["mlm_ln_w"], params["mlm_ln_b"]).astype(dt)
        # stays in the compute dtype: the fused CE (ops/loss.py) reduces in
        # fp32 internally, so fp32 logits would only add HBM traffic
        return x @ params["word_emb"].astype(dt).T + params["mlm_bias"].astype(dt)

    def mlm_logits(self, params, h):
        """fp32 MLM head for external use (eval perplexity, logit inspection),
        mirroring GPT's head_fn/_head_logits split; the loss path uses the
        compute-dtype variant since fused CE reduces in fp32 anyway."""
        return self._mlm_logits(params, h).astype(jnp.float32)

    @staticmethod
    def _additive_mask(attention_mask):
        """(B, L) 1=keep/0=pad → additive (B, 1, 1, L) mask, or None."""
        if attention_mask is None:
            return None
        return (1.0 - attention_mask.astype(jnp.float32))[:, None, None, :] * -1e30

    def pretrain_loss_fn(self, params, input_ids, mlm_labels, nsp_labels=None,
                         token_type_ids=None, attention_mask=None, remat=False):
        """MLM (ignore label -100) + optional NSP loss."""
        h = self.encode(params, input_ids, token_type_ids,
                        attn_mask=self._additive_mask(attention_mask),
                        remat=remat)
        logits = self._mlm_logits(params, h)
        valid = mlm_labels >= 0
        safe = jnp.where(valid, mlm_labels, 0)
        # fused masked CE — no fp32 (B, L, V) log-prob tensor (ops/loss.py)
        from ..ops.loss import softmax_cross_entropy_weighted_mean
        mlm_loss = softmax_cross_entropy_weighted_mean(logits, safe, valid)
        if nsp_labels is None:
            return mlm_loss
        pooled = self.pool_fn(params, h).astype(jnp.float32)
        nsp_logits = pooled @ params["nsp_w"] + params["nsp_b"]
        nsp_logp = jax.nn.log_softmax(nsp_logits, axis=-1)
        nsp_loss = -jnp.take_along_axis(nsp_logp, nsp_labels[:, None],
                                        axis=-1).mean()
        return mlm_loss + nsp_loss

    # ------------------------------------------------------------- nn.Layer
    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        raw = getattr(input_ids, "_data", input_ids)
        tok = getattr(token_type_ids, "_data", token_type_ids)
        am = getattr(attention_mask, "_data", attention_mask)
        params = {n: p._data for n, p in self.named_parameters()}
        h = self.encode(params, raw, tok, attn_mask=self._additive_mask(am))
        pooled = self.pool_fn(params, h)
        if isinstance(input_ids, Tensor):
            return Tensor(h), Tensor(pooled)
        return h, pooled


def make_bert_train_step(model: BertModel, optimizer, hcg, remat: bool = True,
                         donate: bool = True):
    """Data/tensor-parallel MLM+NSP pretraining step over the hybrid mesh."""
    from ..distributed.spmd import make_gspmd_step_from_loss

    params0 = {n: p._data for n, p in model.named_parameters()}

    def loss_of(params, input_ids, mlm_labels, nsp_labels):
        return model.pretrain_loss_fn(params, input_ids, mlm_labels,
                                      nsp_labels, remat=remat)

    return make_gspmd_step_from_loss(loss_of, params0, optimizer, hcg.mesh,
                                     layer=model, donate=donate)


def make_sharded_bert_train_step(cfg: BertConfig, optimizer, hcg,
                                 zero_stage: int = 0, seed: int = 0,
                                 remat: bool = True, donate: bool = True):
    """BERT pretraining step with mesh-direct sharded init (see
    models/gpt.py make_sharded_gpt_train_step — same contract: sharding
    SPECS only; contractual-ZeRO extras ride make_bert_train_step)."""
    from ..core import rng as _rng
    from ..distributed.spmd import make_gspmd_sharded_init_step

    holder = {}

    def build(key):
        with _rng.rng_scope(key):
            m = BertModel(cfg)
        holder.setdefault("model", m)
        return {n: p._data for n, p in m.named_parameters()}

    jax.eval_shape(build, jax.random.key(seed))
    meta = holder["model"]

    def loss_of(params, input_ids, mlm_labels, nsp_labels):
        return meta.pretrain_loss_fn(params, input_ids, mlm_labels,
                                     nsp_labels, remat=remat)

    return make_gspmd_sharded_init_step(loss_of, build, optimizer, hcg.mesh,
                                        meta, zero_stage=zero_stage,
                                        donate=donate, seed=seed)
