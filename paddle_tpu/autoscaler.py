"""Elastic autoscaler: closed-loop SLO-driven scaling of the gateway fleet.

Every piece of the loop already exists and nothing connects them: the SLO
engine (PR 10) judges the service and fires TTFT/shed-rate burn alerts
into a log, the gateway (PR 8) can ``drain()`` a replica with zero drops,
and AOT warmup + the persistent executable cache (PR 6) make a fresh
replica cheap to spin up.  :class:`ElasticAutoscaler` is the controller
that closes the loop — the serving-side analogue of PaddlePaddle's
elastic fleet training, where replicas join and leave a running job
without operator babysitting.

**Signals** (watch side):

- *scale-up*: SLO alert transitions, consumed through the
  ``SLOMonitor.subscribe`` push feed — an objective (TTFT p99, shed
  rate, any objective the monitor carries) entering ``firing`` marks the
  fleet under-provisioned; ``resolved``/``cancelled`` clears it.  The
  autoscaler drives ``slo.evaluate()`` each round, so the alert state
  machine advances on the controller's (injectable) clock.
- *scale-up (resilience)*: OPEN circuit breakers, read from
  ``gateway.breakers_open()`` (the PR 12 resilience layer) — a replica
  whose breaker is open is missing capacity the SLO math has not priced
  in yet, so breaker-open counts as an under-provisioned signal
  alongside firing objectives.  Gateways without a resilience policy
  report none; nothing changes.
- *scale-down*: sustained low utilization.  Utilization is the fleet's
  outstanding-work occupancy — (in-flight requests + queued requests)
  over total engine slots across ACTIVE replicas — optionally
  cross-checked against a ``telemetry_ledger.RunLedger`` goodput gauge.

**Policy** (decide side) — production-shaped, every knob explicit:

- ``min_replicas`` / ``max_replicas`` fleet bounds.  The min bound is
  enforced eagerly: a quarantined/dead replica that leaves the active
  fleet short is replaced immediately, cooldowns notwithstanding.
- one replica per decision (the step limit — no thundering spawns).
- per-direction cooldowns (``scale_up_cooldown_s`` /
  ``scale_down_cooldown_s``); a scale-up also re-arms the scale-down
  cooldown (never tear down what was just added).  A FAILED spawn
  (broken factory, failed activation) arms the scale-up cooldown as a
  retry backoff — even on the otherwise cooldown-exempt min-bound path —
  so a persistently broken factory is retried once per cooldown window,
  not once per ``evaluate()`` round.
- quarantined replicas are reaped (``reap_quarantined=True``): the
  gateway never auto-reinstates a replica it benched, so in an
  autoscaler-managed fleet the benched shell is drained (it holds no
  in-flight work — quarantine already rerouted it) and removed, while
  the min-bound check back-fills the lost capacity.  Set
  ``reap_quarantined=False`` to keep shells registered for operator
  ``reinstate()``.
- scale-down hysteresis matching the SLO engine's dwell semantics:
  occupancy must stay below ``idle_utilization`` for ``idle_dwell_s``
  before a drain, and once the dwell is running only a clear bounce
  ABOVE ``idle_utilization * idle_resume_ratio`` resets it — occupancy
  hovering exactly at the threshold cannot flap decisions (pinned by
  test, the same resolve-band discipline ``telemetry_slo`` uses).

**Actuation** (act side), through existing primitives only:

- scale-up: build an engine from the registered factory
  (``ElasticAutoscaler(factory=...)`` or
  ``gateway.register_replica_factory``), AOT-warm it from the persistent
  executable cache (``engine.warmup(cache_dir=...)``, PR 6), and only
  when warm ``gateway.add_replica()`` it.  Warmup may be synchronous
  (default — the report comes back immediately) or a background future
  (``warm_async=True``); a pending spawn is activated by a later
  ``evaluate()`` once its future resolves.  Engines that cannot warm
  (TP/mesh engines raise ``NotImplementedError``) are activated unwarmed.
- every spawned replica's warmup grid is registered on its tracer via a
  held-open ``Tracer.expected_compiles(keys=engine.compile_grid())``
  window, so the PR 2 recompile-storm warning ignores expected
  first-dispatch misses on a freshly activated replica (the window is
  keyed to the grid — a real storm of off-grid misses still arms it);
  the window closes when the replica is drained or the autoscaler is
  ``close()``d.
- scale-down: pick the least-loaded ACTIVE replica and
  ``gateway.drain()`` it with no replacement — zero drops by the drain
  contract — then ``gateway.remove_replica()`` the stopped shell.

**Observability**: every decision is emitted as a tracer ``autoscale``
event and kept in a bounded decision history; ``prometheus_text()``
exports fleet-size / pending-spawn / last-decision gauges and per-action
counters; ``autoscaler_snapshot()`` is the ``GET /autoscaler`` ops view
(``ops_server.OpsServer.attach(autoscaler)``).

The clock is injectable, so whole scale-up/scale-down trajectories run
deterministically on the fake-clock simulation harness
(``paddle_tpu.simulation``) — see docs/AUTOSCALING.md.

Typical use::

    slo = SLOMonitor([Objective.latency("ttft_p99", "ttft_s", 0.5),
                      Objective.ratio("shed_rate", "shed", "submitted",
                                      0.05)])
    gw.set_slo(slo)
    asc = ElasticAutoscaler(gw, factory, slo=slo, min_replicas=1,
                            max_replicas=8, cache_dir="/var/cache/xla")
    while serving:
        gw.step()
        asc.evaluate()          # one control round per serving round

No reference counterpart: the reference snapshot has no service layer;
this composes the PR 6/8/10 primitives into the control plane the
ROADMAP's elastic-fleet item names.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .utils.stats import StatRegistry, prometheus_text as _prometheus_text

__all__ = ["ElasticAutoscaler", "DECISIONS"]

#: decision vocabulary, in gauge-encoding order (0 = none yet)
DECISIONS = ("none", "scale_up", "activate", "scale_down", "removed",
             "spawn_failed", "reap")


class _PendingSpawn:
    """One spawned-but-not-yet-active replica: the engine, its warmup
    future (None when warmup completed synchronously or was skipped), and
    the decision metadata the activation event echoes."""

    __slots__ = ("engine", "name", "future", "report", "warmed",
                 "started_at", "reason")

    def __init__(self, engine, name, future, report, warmed, started_at,
                 reason):
        self.engine = engine
        self.name = name
        self.future = future
        self.report = report
        self.warmed = warmed
        self.started_at = started_at
        self.reason = reason

    def ready(self) -> bool:
        return self.future is None or self.future.done()

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "engine": type(self.engine).__name__,
                "warmed": self.warmed, "started_at": self.started_at,
                "reason": self.reason,
                "pending_future": self.future is not None
                and not self.future.done()}


def _engine_slots(engine) -> int:
    """Slot capacity of one engine — the serving engines expose ``S``
    (max_slots); anything else counts as one slot."""
    for attr in ("S", "max_slots"):
        v = getattr(engine, attr, None)
        if isinstance(v, int) and v > 0:
            return v
    return 1


class ElasticAutoscaler:
    """Closed-loop SLO-driven fleet scaling (module docstring).

    ``gateway``: the :class:`~paddle_tpu.gateway.ServingGateway` to scale.
    ``factory``: zero-arg engine factory; falls back to the gateway's
    ``register_replica_factory`` registration.  ``slo``: the
    :class:`~paddle_tpu.telemetry_slo.SLOMonitor` whose firing objectives
    drive scale-up (``objectives=`` restricts to a subset of names; None
    watches all).  ``ledger``: optional
    :class:`~paddle_tpu.telemetry_ledger.RunLedger` whose goodput gauge
    rides along in the utilization signal.  ``fleet`` +
    ``fleet_ttft_high``: optional
    :class:`~paddle_tpu.telemetry_fleet.FleetCollector` whose MERGED
    fleet TTFT p99 at/over the threshold is a scale-up trigger — the
    cross-process signal a purely local monitor cannot see.
    ``cache_dir``: the PR 6
    persistent executable cache new replicas warm from.  ``clock``:
    injectable monotonic-seconds callable — the whole policy is
    deterministic under a fake clock."""

    def __init__(self, gateway, factory: Optional[Callable[[], Any]] = None,
                 *, slo=None, ledger=None, objectives=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_cooldown_s: float = 30.0,
                 scale_down_cooldown_s: float = 120.0,
                 idle_utilization: float = 0.15,
                 idle_dwell_s: float = 60.0,
                 idle_resume_ratio: float = 1.5,
                 decode_pool_high: Optional[float] = None,
                 fleet=None, fleet_ttft_high: Optional[float] = None,
                 cache_dir: Optional[str] = None,
                 warm_async: bool = False,
                 reap_quarantined: bool = True,
                 tracer=None, clock: Callable[[], float] = time.monotonic,
                 decision_history: int = 256, name_prefix: str = "as",
                 logger: Optional[logging.Logger] = None):
        if int(min_replicas) < 1:
            raise ValueError("min_replicas must be >= 1")
        if int(max_replicas) < int(min_replicas):
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 < float(idle_utilization) < 1.0:
            raise ValueError("idle_utilization must be in (0, 1)")
        if float(idle_resume_ratio) < 1.0:
            raise ValueError("idle_resume_ratio must be >= 1.0 (the "
                             "hysteresis band sits ABOVE the threshold)")
        self.gateway = gateway
        self._factory = factory
        self.slo = slo
        self.ledger = ledger
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_cooldown_s = float(scale_up_cooldown_s)
        self.scale_down_cooldown_s = float(scale_down_cooldown_s)
        self.idle_utilization = float(idle_utilization)
        self.idle_dwell_s = float(idle_dwell_s)
        self.idle_resume_ratio = float(idle_resume_ratio)
        # disaggregation-aware signal (docs/KV_TIERING.md): when set,
        # gateway.decode_pool_pressure() at or above this threshold is a
        # scale-up trigger alongside firing SLOs and open breakers — a
        # drowning decode pool behind idle prefill replicas would
        # otherwise hide inside fleet-wide occupancy
        if decode_pool_high is not None and float(decode_pool_high) <= 0:
            raise ValueError("decode_pool_high must be > 0 (or None)")
        self.decode_pool_high = (None if decode_pool_high is None
                                 else float(decode_pool_high))
        # fleet-level signal (docs/OBSERVABILITY.md "Fleet"): when a
        # telemetry_fleet.FleetCollector is attached, the MERGED TTFT
        # p99 at/over fleet_ttft_high seconds is a scale-up trigger — a
        # replica group can be drowning fleet-wide while this process's
        # local SLO monitor, seeing only its own slice, stays quiet
        if fleet is not None and not hasattr(fleet, "fleet_snapshot"):
            raise TypeError(f"fleet= wants a FleetCollector-like object "
                            f"with fleet_snapshot(), got "
                            f"{type(fleet).__name__}")
        if fleet_ttft_high is not None and float(fleet_ttft_high) <= 0:
            raise ValueError("fleet_ttft_high must be > 0 (or None)")
        self.fleet = fleet
        self.fleet_ttft_high = (None if fleet_ttft_high is None
                                else float(fleet_ttft_high))
        self.cache_dir = cache_dir
        self.warm_async = bool(warm_async)
        self.reap_quarantined = bool(reap_quarantined)
        self.tracer = tracer
        self._clock = clock
        self.name_prefix = str(name_prefix)
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._watched = (None if objectives is None
                         else frozenset(str(n) for n in objectives))
        # _firing is mutated from SLO subscriber callbacks, which run on
        # whatever thread drives slo.evaluate() — including ops-server
        # HTTP scrape threads when the monitor is attached there — so
        # every access goes through _firing_lock
        self._firing_lock = threading.Lock()
        self._firing: set = set()
        # pending spawns and the decision ring are appended on the
        # evaluate path but read by ops-server scrape threads
        # (/autoscaler, metrics, prometheus_text); _state_lock is held
        # only for list/deque ops, never across warmup or logging
        self._state_lock = threading.Lock()
        self._pending: List[_PendingSpawn] = []  # guarded-by: _state_lock
        self._draining: List[str] = []     # names this controller drained
        self._spawn_seq = 0
        self._last_up_at: Optional[float] = None
        self._last_down_at: Optional[float] = None
        self._last_spawn_failure_at: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_decision = "none"
        self._last_decision_at: Optional[float] = None
        self._decisions: collections.deque = collections.deque(  # guarded-by: _state_lock
            maxlen=int(decision_history))
        # held-open expected-compile windows, keyed by replica name: the
        # entered context managers are exited on drain/close
        self._expected_windows: Dict[str, Any] = {}
        self._stats = StatRegistry()
        self._closed = False
        if slo is not None:
            slo.subscribe(self._on_slo_transition)
            # seed from the monitor's current states: an autoscaler
            # attached mid-incident must see the already-firing alert
            seeded = {name for name, state in slo.alert_states().items()
                      if state == "firing" and self._watches(name)}
            with self._firing_lock:
                self._firing |= seeded

    # ----------------------------------------------------------- signals --

    def _watches(self, objective_name: str) -> bool:
        return self._watched is None or objective_name in self._watched

    def _on_slo_transition(self, ev: Dict[str, Any]):
        """``SLOMonitor.subscribe`` callback — runs under the monitor's
        evaluation lock, so it only updates local state (never calls back
        into the monitor)."""
        name = ev.get("objective")
        if name is None or not self._watches(name):
            return
        what = ev.get("what")
        with self._firing_lock:
            if what == "firing":
                self._firing.add(name)
            elif what in ("resolved", "cancelled"):
                self._firing.discard(name)

    def firing(self) -> List[str]:
        """Objective names currently firing (the scale-up signal)."""
        with self._firing_lock:
            return sorted(self._firing)

    def breakers_open(self) -> List[str]:
        """Replica names whose gateway circuit breaker is OPEN (the
        resilience-side scale-up signal); empty when the gateway has no
        resilience layer — or a broken one (a poll failure must not take
        the controller down)."""
        get = getattr(self.gateway, "breakers_open", None)
        if get is None:
            return []
        try:
            return list(get())
        except Exception as e:  # noqa: BLE001 — pull-source discipline,
            # same as the ledger poll
            self._log.debug("autoscaler: breaker poll failed: %r", e)
            return []

    def decode_pool_pressure(self) -> Optional[float]:
        """The gateway's decode-pool occupancy ((in-flight + queued +
        migrating) over ACTIVE non-prefill slots), or None when the
        gateway predates the disaggregation surface or the poll fails
        (pull-source discipline — a broken signal never takes the
        controller down)."""
        get = getattr(self.gateway, "decode_pool_pressure", None)
        if get is None:
            return None
        try:
            return float(get())
        except Exception as e:  # noqa: BLE001 — same guard as the
            # breaker/ledger polls
            self._log.debug("autoscaler: decode-pool poll failed: %r", e)
            return None

    def _decode_pool_hot(self) -> Optional[float]:
        """The pressure value when it is at/over ``decode_pool_high``
        (the scale-up trigger), else None (signal disabled or cool)."""
        if self.decode_pool_high is None:
            return None
        p = self.decode_pool_pressure()
        if p is not None and p >= self.decode_pool_high:
            return p
        return None

    def fleet_ttft_p99(self) -> Optional[float]:
        """The attached collector's merged fleet TTFT p99 (seconds), or
        None when no collector is attached, it has not scraped yet, or
        the poll fails (pull-source discipline — a broken signal never
        takes the controller down)."""
        if self.fleet is None:
            return None
        try:
            rollup = self.fleet.fleet_snapshot().get("rollup") or {}
            v = rollup.get("fleet_ttft_p99")
            return None if v is None else float(v)
        except Exception as e:  # noqa: BLE001 — same guard as the
            # breaker/ledger/decode-pool polls
            self._log.debug("autoscaler: fleet poll failed: %r", e)
            return None

    def _fleet_hot(self) -> Optional[float]:
        """The merged TTFT p99 when it is at/over ``fleet_ttft_high``
        (the scale-up trigger), else None (signal disabled or cool)."""
        if self.fleet_ttft_high is None:
            return None
        v = self.fleet_ttft_p99()
        if v is not None and v >= self.fleet_ttft_high:
            return v
        return None

    def utilization(self) -> Dict[str, Any]:
        """The scale-down signal: fleet occupancy — (in-flight + queued)
        requests over total ACTIVE engine slots — plus the raw terms and,
        when a ledger is attached, its goodput gauge."""
        active = [rep for rep in self.gateway.replicas()
                  if rep.state == "active"]
        slots = sum(_engine_slots(rep.engine) for rep in active)
        busy = sum(len(rep.inflight) for rep in active)
        queued = sum(d["depth"]
                     for d in self.gateway.queue_depths().values())
        outstanding = sum(rep.outstanding_tokens() for rep in active)
        goodput = None
        if self.ledger is not None:
            try:
                goodput = float(self.ledger.snapshot()["goodput"])
            except Exception as e:  # noqa: BLE001 — a broken pull source
                # must not take the controller down
                self._log.debug("autoscaler: ledger pull failed: %r", e)
        return {"occupancy": (busy + queued) / max(slots, 1),
                "busy_slots": busy, "total_slots": slots,
                "queued": queued, "outstanding_tokens": outstanding,
                "goodput": goodput}

    # ------------------------------------------------------------- fleet --

    def _fleet(self):
        reps = self.gateway.replicas()
        active = [r for r in reps if r.state == "active"]
        draining = [r for r in reps if r.state == "draining"]
        return active, draining

    def fleet_size(self) -> int:
        """Replicas that hold (or will hold) serving capacity: active +
        draining + pending spawns — what the max bound is checked
        against."""
        active, draining = self._fleet()
        with self._state_lock:
            pending = len(self._pending)
        return len(active) + len(draining) + pending

    # ---------------------------------------------------------- evaluate --

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One control round: advance the SLO state machine, activate any
        warm pending spawns, garbage-collect completed drains, then make
        at most ONE scale decision (the step limit).  Returns the
        decisions recorded this round (possibly empty).  Deterministic
        for an injected clock; safe to call every gateway round."""
        if self._closed:
            return []
        now = self._clock() if now is None else float(now)
        made: List[Dict[str, Any]] = []
        if self.slo is not None:
            # drives transitions → the subscription updates self._firing
            self.slo.evaluate(now)
        made.extend(self._activate_ready(now))
        made.extend(self._reap_quarantined(now))
        made.extend(self._reap_drained(now))
        decision = self._decide(now)
        if decision is not None:
            made.append(decision)
        return made

    def _decide(self, now: float) -> Optional[Dict[str, Any]]:
        active, draining = self._fleet()
        util = self.utilization()
        firing = self.firing()
        # min-bound enforcement first, cooldown-exempt: a quarantined or
        # dead replica that left the fleet short is replaced NOW (only
        # the spawn-FAILURE backoff gates it — a persistently broken
        # factory must not be retried every round)
        with self._state_lock:
            n_pending = len(self._pending)
        if len(active) + n_pending < self.min_replicas:
            if self._spawn_backoff(now):
                return None
            return self._spawn(now, reason="min_bound", firing=firing,
                               utilization=util)
        breakers = self.breakers_open()
        decode_hot = self._decode_pool_hot()
        fleet_hot = self._fleet_hot()
        if firing or breakers or decode_hot is not None \
                or fleet_hot is not None:
            self._idle_since = None          # under-provisioned ≠ idle
            in_up_cooldown = (
                self._last_up_at is not None
                and now - self._last_up_at < self.scale_up_cooldown_s)
            if self.fleet_size() < self.max_replicas \
                    and not in_up_cooldown and not self._spawn_backoff(now):
                parts = []
                if firing:
                    parts.append("slo:" + ",".join(firing))
                if breakers:
                    parts.append("breaker:" + ",".join(breakers))
                if decode_hot is not None:
                    parts.append(f"decode_pool:{decode_hot:.2f}")
                if fleet_hot is not None:
                    parts.append(f"fleet_ttft:{fleet_hot:.3f}")
                return self._spawn(now, reason="+".join(parts),
                                   firing=firing, utilization=util)
            return None
        self._track_idle(now, util["occupancy"])
        if self._scale_down_ok(now, active):
            return self._drain_one(now, active, utilization=util)
        return None

    def _spawn_backoff(self, now: float) -> bool:
        """True while the spawn-failure retry backoff is running: a
        failed spawn (broken factory, failed activation) re-arms it for
        one ``scale_up_cooldown_s`` window, bounding retries of a
        persistently broken factory to one per window instead of one per
        ``evaluate()`` round (which would flood the log and churn the
        decision history/tracer ring with identical failures)."""
        return (self._last_spawn_failure_at is not None
                and now - self._last_spawn_failure_at
                < self.scale_up_cooldown_s)

    def _track_idle(self, now: float, occupancy: float):
        """The idle-dwell state machine (hysteresis, module docstring):
        dwell starts below ``idle_utilization`` and only a bounce above
        ``idle_utilization * idle_resume_ratio`` cancels it."""
        if occupancy < self.idle_utilization:
            if self._idle_since is None:
                self._idle_since = now
        elif occupancy >= self.idle_utilization * self.idle_resume_ratio:
            self._idle_since = None

    def _scale_down_ok(self, now: float, active) -> bool:
        if len(active) <= self.min_replicas:
            return False
        if self._idle_since is None \
                or now - self._idle_since < self.idle_dwell_s:
            return False
        for stamp, cool in ((self._last_down_at,
                             self.scale_down_cooldown_s),
                            (self._last_up_at,
                             self.scale_down_cooldown_s)):
            # a recent scale-up also blocks scale-down: never tear down
            # what was just added
            if stamp is not None and now - stamp < cool:
                return False
        return True

    # ----------------------------------------------------------- actuate --

    def _spawn(self, now: float, reason: str, firing, utilization
               ) -> Dict[str, Any]:
        factory = self._factory
        if factory is None:
            factory = getattr(self.gateway, "replica_factory", None)
        if factory is None:
            self._stats.add("spawn_failures")
            self._last_spawn_failure_at = now
            return self._record(now, "spawn_failed", reason=reason,
                                error="no engine factory registered")
        name = f"{self.name_prefix}{self._spawn_seq}"
        self._spawn_seq += 1
        try:
            engine = factory()
        except Exception as e:  # noqa: BLE001 — a broken factory must not
            # take the control loop down; the failure is a recorded
            # decision the operator sees
            self._log.exception("autoscaler: engine factory failed")
            self._stats.add("spawn_failures")
            self._last_spawn_failure_at = now
            return self._record(now, "spawn_failed", reason=reason,
                                error=repr(e))
        future = report = None
        warmed = False
        try:
            res = engine.warmup(cache_dir=self.cache_dir,
                                block=not self.warm_async)
            if hasattr(res, "done") and hasattr(res, "result"):
                future = res
            else:
                report = res
            warmed = True
        except NotImplementedError as e:
            # TP/mesh engines compile on first dispatch (serving.py); the
            # replica still joins — its grid window (opened at activation)
            # keeps the storm warning honest about first-dispatch misses
            self._log.debug("autoscaler: warmup unsupported for %s: %r",
                            type(engine).__name__, e)
        except Exception as e:  # noqa: BLE001 — warmup is best-effort:
            # an unwarmed replica is strictly better than no replica
            self._log.warning("autoscaler: warmup failed for %s: %r",
                              name, e)
        with self._state_lock:
            self._pending.append(_PendingSpawn(engine, name, future, report,
                                               warmed, now, reason))
        self._last_up_at = now
        self._stats.add("scale_ups")
        return self._record(
            now, "scale_up", replica=name, reason=reason,
            warmed=warmed, pending=future is not None,
            firing=list(firing), occupancy=utilization["occupancy"])

    def _activate_ready(self, now: float) -> List[Dict[str, Any]]:
        made = []
        with self._state_lock:
            pending = list(self._pending)
        for spawn in pending:
            if not spawn.ready():
                continue
            with self._state_lock:
                self._pending.remove(spawn)
            if spawn.future is not None:
                try:
                    spawn.report = spawn.future.result()
                except Exception as e:  # noqa: BLE001 — a failed async
                    # warmup downgrades to unwarmed activation, same as
                    # the synchronous path
                    self._log.warning("autoscaler: async warmup failed "
                                      "for %s: %r", spawn.name, e)
                    spawn.warmed = False
            try:
                name = self.gateway.add_replica(spawn.engine, spawn.name)
            except (TypeError, ValueError) as e:
                self._log.exception("autoscaler: activation failed for %s",
                                    spawn.name)
                self._stats.add("spawn_failures")
                self._last_spawn_failure_at = now
                made.append(self._record(now, "spawn_failed",
                                         replica=spawn.name,
                                         error=repr(e)))
                continue
            self._open_expected_window(name, spawn.engine)
            self._stats.add("activations")
            made.append(self._record(
                now, "activate", replica=name, reason=spawn.reason,
                warmed=spawn.warmed,
                warm_programs=(spawn.report or {}).get("programs")
                if isinstance(spawn.report, dict) else None,
                spawn_wait_s=now - spawn.started_at))
        return made

    def _drain_one(self, now: float, active, utilization) -> Dict[str, Any]:
        victim = min(active, key=lambda rep: (rep.outstanding_tokens(),
                                              len(rep.inflight), rep.name))
        self.gateway.drain(victim.name)       # no replacement: fleet shrinks
        self._draining.append(victim.name)
        self._last_down_at = now
        self._idle_since = None               # dwell restarts after acting
        self._stats.add("scale_downs")
        return self._record(
            now, "scale_down", replica=victim.name, reason="idle",
            occupancy=utilization["occupancy"],
            inflight=len(victim.inflight))

    def _reap_quarantined(self, now: float) -> List[Dict[str, Any]]:
        """Retire quarantined shells (module docstring): the gateway
        never auto-reinstates a replica it benched, and a long-lived
        elastic fleet must not accumulate one dead entry per death — so
        each quarantined replica is sent through the zero-drop ``drain``
        path (it holds no in-flight work; quarantine already rerouted
        it) and removed by ``_reap_drained`` once stopped, while the
        min-bound check back-fills the capacity.  Disabled with
        ``reap_quarantined=False`` (operator wants ``reinstate()``)."""
        if not self.reap_quarantined:
            return []
        made = []
        for rep in self.gateway.replicas():
            if rep.state != "quarantined" or rep.name in self._draining:
                continue
            self.gateway.drain(rep.name)       # no replacement: min-bound
            self._draining.append(rep.name)    # spawns the back-fill
            self._stats.add("reaps")
            made.append(self._record(now, "reap", replica=rep.name,
                                     reason=rep.reason or "quarantined"))
        return made

    def _reap_drained(self, now: float) -> List[Dict[str, Any]]:
        made = []
        still = []
        for name in self._draining:
            try:
                drained = self.gateway.is_drained(name)
            except KeyError:
                # already removed (operator raced us): nothing to reap
                self._close_expected_window(name)
                continue
            if not drained:
                still.append(name)
                continue
            self._close_expected_window(name)
            try:
                self.gateway.remove_replica(name)
            except (KeyError, ValueError) as e:
                self._log.debug("autoscaler: remove_replica(%s): %r",
                                name, e)
            self._stats.add("removals")
            made.append(self._record(now, "removed", replica=name))
        self._draining = still
        return made

    # ----------------------------------------- expected-compile windows --

    def _open_expected_window(self, name: str, engine):
        """Register the replica's warmup grid on its tracer via a
        held-open ``expected_compiles`` window (module docstring): the
        recompile-storm warning ignores the grid's first-dispatch misses
        on this freshly activated replica.  Safe to hold open — a grid
        label can only miss once per program cache, so the window never
        masks a real storm (off-grid misses still count)."""
        tracer = getattr(engine, "tracer", None)
        if tracer is None or not hasattr(tracer, "expected_compiles"):
            return
        try:
            keys = set(engine.compile_grid())
        except (AttributeError, NotImplementedError, ValueError) as e:
            self._log.debug("autoscaler: no compile grid for %s: %r",
                            name, e)
            return
        if not keys:
            return
        ctx = tracer.expected_compiles(keys=keys)
        ctx.__enter__()
        self._expected_windows[name] = ctx

    def _close_expected_window(self, name: str):
        ctx = self._expected_windows.pop(name, None)
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001 — window teardown is
                # best-effort; a broken tracer must not stop the reap
                self._log.debug("autoscaler: expected window close "
                                "failed for %s: %r", name, e)

    def close(self):
        """Detach from the SLO monitor and close every held-open
        expected-compile window; further ``evaluate()`` calls are
        no-ops.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self.slo is not None:
            self.slo.unsubscribe(self._on_slo_transition)
        for name in list(self._expected_windows):
            self._close_expected_window(name)

    # ------------------------------------------------------ observability --

    def _record(self, now: float, action: str, **fields) -> Dict[str, Any]:
        active, draining = self._fleet()
        with self._state_lock:
            pending = len(self._pending)
        ev = {"ts": now, "action": action,
              "fleet_active": len(active),
              "fleet_draining": len(draining),
              "pending_spawns": pending}
        ev.update({k: v for k, v in fields.items() if v is not None})
        with self._state_lock:
            self._decisions.append(ev)
        self._last_decision = action
        self._last_decision_at = now
        if self.tracer is not None:
            self.tracer.emit("autoscale", what=action, at=now,
                             **{k: v for k, v in ev.items()
                                if k not in ("ts", "action")})
        log = (self._log.info if action in ("scale_up", "activate",
                                            "scale_down", "removed")
               else self._log.warning)
        log("autoscale %s: %s (fleet %d active / %d draining / %d "
            "pending)", action, fields.get("reason", fields.get(
                "error", "")), ev["fleet_active"], ev["fleet_draining"],
            ev["pending_spawns"])
        return ev

    def decisions(self) -> List[Dict[str, Any]]:
        """The bounded decision history, oldest first."""
        with self._state_lock:
            return list(self._decisions)

    def autoscaler_snapshot(self) -> Dict[str, Any]:
        """JSON-able live view — what ``ops_server``'s ``/autoscaler``
        route serves: policy knobs, fleet state, live signals, pending
        spawns, cooldown/dwell clocks, and the decision history."""
        now = self._clock()
        active, draining = self._fleet()
        with self._state_lock:
            pending = list(self._pending)
        return {
            "now": now,
            "policy": {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "scale_up_cooldown_s": self.scale_up_cooldown_s,
                "scale_down_cooldown_s": self.scale_down_cooldown_s,
                "idle_utilization": self.idle_utilization,
                "idle_dwell_s": self.idle_dwell_s,
                "idle_resume_ratio": self.idle_resume_ratio,
                "warm_async": self.warm_async,
                "reap_quarantined": self.reap_quarantined,
                "cache_dir": self.cache_dir,
                "objectives": (None if self._watched is None
                               else sorted(self._watched)),
            },
            "fleet": {"active": len(active), "draining": len(draining),
                      "pending_spawns": len(pending),
                      "replicas": [rep.to_dict()
                                   for rep in active + draining]},
            "pending": [s.to_dict() for s in pending],
            "signals": {"firing": self.firing(),
                        "breakers_open": self.breakers_open(),
                        "decode_pool_pressure": self.decode_pool_pressure(),
                        "decode_pool_high": self.decode_pool_high,
                        "fleet_ttft_p99": self.fleet_ttft_p99(),
                        "fleet_ttft_high": self.fleet_ttft_high,
                        "utilization": self.utilization(),
                        "idle_since": self._idle_since,
                        "idle_for_s": (None if self._idle_since is None
                                       else now - self._idle_since)},
            "cooldowns": {
                "last_scale_up_at": self._last_up_at,
                "last_scale_down_at": self._last_down_at,
                "last_spawn_failure_at": self._last_spawn_failure_at},
            "last_decision": self._last_decision,
            "last_decision_at": self._last_decision_at,
            "counters": dict(self._stats.snapshot()),
            "decisions": self.decisions(),
            "closed": self._closed,
        }

    def metrics(self) -> Dict[str, float]:
        active, draining = self._fleet()
        out = dict(self._stats.snapshot())
        out["fleet_active"] = float(len(active))
        out["fleet_draining"] = float(len(draining))
        with self._state_lock:
            out["pending_spawns"] = float(len(self._pending))
        with self._firing_lock:
            out["alerts_firing"] = float(len(self._firing))
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_autoscaler"
                        ) -> str:
        active, draining = self._fleet()
        with self._state_lock:
            pending = len(self._pending)
        with self._firing_lock:
            firing = len(self._firing)
        return _prometheus_text(
            self._stats, namespace=namespace,
            extra_gauges={
                "fleet_size": len(active),
                "fleet_draining": len(draining),
                "pending_spawns": pending,
                "alerts_firing": firing,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                # enum gauge: index into DECISIONS (0 = no decision yet)
                "last_decision": DECISIONS.index(self._last_decision)
                if self._last_decision in DECISIONS else 0})
