"""Continuous-batching serving engine for the causal decoder stack.

No reference counterpart at this granularity — the reference snapshot's
decode machinery is MultiHeadAttention.Cache incremental k/v
(python/paddle/nn/layer/transformer.py:151) driven whole-batch by
BeamSearchDecoder/dynamic_decode (python/paddle/nn/decode.py): batches are
admitted and retired together.  (The later-Paddle ecosystem adds
fused_multi_transformer CacheKV serving — not in this snapshot.)  This engine
is the TPU-native upgrade: requests join and leave a running decode batch at
any step (the JetStream/Orca "continuous batching" discipline), while every
device program stays STATIC-shape so XLA compiles each signature exactly
once:

- one global KV cache of ``max_slots`` rows (a slot = one in-flight request,
  layout (num_layers, S, max_len, nh, hd) — slot is the batch index);
- admission runs a per-bucket prefill program that writes ONE slot's cache
  region (prompts are left-padded to the bucket length; the mixin's
  ``pad_lens`` machinery masks pad keys and shifts positions);
- every decode tick is ONE compiled step over all S slots with per-row cache
  clocks (``write_cache``/``cached_attention`` per-row ``t`` — the same
  scatter form batched speculative decoding uses); inactive slots are
  carried inert: their clock is frozen and their stale writes land at
  positions a future occupant overwrites before it can ever read them
  (decode at position u writes u before attending ≤ u).

Typical use::

    eng = ContinuousBatchingEngine(model, params, max_slots=8, max_len=256)
    rid = eng.add_request([12, 71, 9], max_new_tokens=32)
    while eng.pending():          # interleaves admission + batched decode
        eng.step()
    out = eng.pop_finished()[rid]

Greedy by default; temperature/top-k/top-p sampling share the engine key.
"""

from __future__ import annotations

import itertools
import logging
import time
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .jit.bucketing import select_bucket
from .telemetry import program_label
from .utils.stats import StatRegistry, stat_add
from .utils.stats import prometheus_text as _prometheus_text
from .models._decode import (apply_repetition_penalty, make_row_sampler,
                             make_token_sampler, seed_presence,
                             suppress_eos, suppress_eos_rows,
                             validate_sampler_args)

__all__ = ["ContinuousBatchingEngine", "SpeculativeBatchingEngine",
           "Request"]


def _timed_first_dispatch(run, cb):
    """Wrap a freshly built program so its FIRST invocation — the one that
    pays trace + XLA compile — is timed end-to-end (block_until_ready) and
    reported through ``cb(seconds, args, kwargs)`` (the call operands ride
    along so the callback can re-lower for cost attribution).  Only
    installed when a tracer is attached at build time; later invocations
    are one bool check."""
    state = [False]

    def wrapped(*a, **kw):
        if state[0]:
            return run(*a, **kw)
        t0 = time.perf_counter()
        out = run(*a, **kw)
        jax.block_until_ready(out)
        state[0] = True
        cb(time.perf_counter() - t0, a, kw)
        return out

    return wrapped


def _program_cost(run, a, kw):
    """Best-effort XLA cost analysis for a jitted program at its observed
    call signature: re-lower (cheap) and consult the process-wide
    digest-keyed cost cache (hapi/dynamic_flops — ONE compile per
    distinct program per process).  None on any failure; never raises —
    MFU attribution must not break serving."""
    try:
        from .hapi.dynamic_flops import cost_of_lowered
        return cost_of_lowered(run.lower(*a, **kw))
    except Exception:  # noqa: BLE001 — best-effort telemetry only
        logging.getLogger(__name__).debug(
            "serving cost attribution failed", exc_info=True)
        return None


def _default_buckets(max_len: int) -> List[int]:
    """The engines' default prompt-bucket ladder for ``max_len`` — ONE
    copy shared by the base constructor and the speculative shims (which
    need the resolved ladder before construction to derive a block
    size)."""
    return [b for b in (16, 32, 64, 128, 256, 512, 1024)
            if b <= max_len] or [int(max_len)]


def _slot_write(slot):
    """Tree-mapper writing one slot's region of a global cache leaf
    (rank-generic: int8 caches pair a 5D value plane with a 4D scale
    plane; slot is the batch dim at axis 1)."""
    def put(big, new):
        return jax.lax.dynamic_update_slice(
            big, new.astype(big.dtype), (0, slot) + (0,) * (big.ndim - 2))
    return put


class Request:
    """One in-flight generation request (host-side bookkeeping)."""

    def __init__(self, rid: int, prompt: List[int], max_new_tokens: int):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.generated: List[int] = []
        self.done = False
        self.enqueued_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.on_token = None          # optional streaming callback

    def __repr__(self):
        return (f"Request(id={self.id}, prompt_len={len(self.prompt)}, "
                f"generated={len(self.generated)}, done={self.done})")


class ContinuousBatchingEngine:
    """Slot-scheduled continuous batching over a CausalDecoderMixin model.

    ``max_slots`` bounds concurrent requests; ``max_len`` bounds
    prompt+generation length per request (one request's logical positions
    must also fit max_position_embeddings).  ``prompt_buckets`` quantizes
    admission prefills so the number of compiled prefill programs is
    len(buckets), not len(distinct prompt lengths).
    """

    def __init__(self, model, params, max_slots: int, max_len: int,
                 prompt_buckets=None, temperature: float = 1.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 greedy: bool = True, eos_token_id: Optional[int] = None,
                 key=None, ticks_per_sync: int = 1, mesh=None,
                 repetition_penalty: float = 1.0, min_new_tokens: int = 0,
                 prefill_chunk: Optional[int] = None,
                 per_request_sampling: bool = False, tracer=None):
        """``ticks_per_sync``: decode ticks fused into one device program
        between host synchronizations.  1 = retire/admit after every token
        (lowest latency); k > 1 amortizes the host round-trip over k tokens
        — tokens a request emits past its EOS/budget inside a chunk are
        discarded host-side (wasted compute < k per request), and a slot
        retires when it lacks room for a FULL chunk, stranding at most k-1
        cache positions.  Greedy outputs are identical for any k.

        ``mesh``: optional ``jax.sharding.Mesh`` with a "model" axis for
        tensor-parallel serving — params are placed by their
        ``_dims_mapping`` specs (the same metadata the training path uses)
        and the KV cache shards over the heads dim; GSPMD inserts the TP
        collectives in the prefill/decode programs exactly as it does for
        training.

        ``repetition_penalty`` / ``min_new_tokens``: the generate()
        processors, engine-wide — a per-slot (S, V) presence plane rides
        next to the KV cache (reset and seeded by admission prefill), and
        EOS windows are per-row (each request's own emission count).

        ``prefill_chunk``: admission prefills at most this many prompt
        positions per scheduler round (must divide every bucket), so one
        long prompt cannot stall every running request's decode for a full
        prefill — the head-of-line latency fix.  None = whole-bucket
        prefill in one round.

        ``tracer``: optional ``paddle_tpu.telemetry.Tracer``; when set the
        engine emits per-tick, per-compile, and per-request structured
        events (host-side only — compiled programs are identical with or
        without it).  None (default) keeps the scheduler hot path at a
        single attribute check: no event allocation, no tracer lock."""
        c = model.config
        if max_len > c.max_position_embeddings:
            raise ValueError(f"max_len {max_len} exceeds "
                             f"max_position_embeddings "
                             f"({c.max_position_embeddings})")
        self._key = key if key is not None else jax.random.key(0)
        validate_sampler_args(c.vocab_size, top_k, top_p, greedy,
                              None if greedy else self._key)
        self.model = model
        self.params = params
        self.S = int(max_slots)
        self.max_len = int(max_len)
        if prompt_buckets is None:
            prompt_buckets = _default_buckets(max_len)
        self.buckets = sorted(set(int(b) for b in prompt_buckets))
        self.eos_token_id = eos_token_id
        self.ticks_per_sync = int(ticks_per_sync)
        if self.ticks_per_sync < 1:
            raise ValueError("ticks_per_sync must be >= 1")
        self.prefill_chunk = None if prefill_chunk is None \
            else int(prefill_chunk)
        if self.prefill_chunk is not None:
            if self.prefill_chunk < 1:
                raise ValueError("prefill_chunk must be >= 1")
            # only buckets that actually chunk (b > chunk) need to divide;
            # smaller buckets take the whole-bucket path untouched
            chunked = [b for b in self.buckets if b > self.prefill_chunk]
            bad = [b for b in chunked if b % self.prefill_chunk]
            if bad:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must divide "
                    f"every prompt bucket it chunks; doesn't divide {bad}")
            if chunked and max(chunked) + self.ticks_per_sync > self.max_len:
                # a filling slot's stale decode writes park in the strip
                # [max_len - ticks_per_sync, max_len); it must sit ABOVE
                # the largest chunked bucket or parking would clobber the
                # prompt region being filled (see _admit)
                raise ValueError(
                    f"chunked prefill needs max_len >= largest chunked "
                    f"bucket ({max(chunked)}) + ticks_per_sync "
                    f"({self.ticks_per_sync}) as a stale-write parking "
                    f"strip; max_len is {self.max_len}")
        self.repetition_penalty = float(repetition_penalty)
        self.min_new_tokens = int(min_new_tokens)
        if self.repetition_penalty <= 0:
            raise ValueError("repetition_penalty must be > 0")
        if self.min_new_tokens > 0 and eos_token_id is None:
            raise ValueError("min_new_tokens needs eos_token_id")
        if eos_token_id is not None and \
                not 0 <= eos_token_id < c.vocab_size:
            raise ValueError(f"eos_token_id {eos_token_id} outside vocab "
                             f"(size {c.vocab_size})")
        self._track = self.repetition_penalty != 1.0
        self._sample_sig = (float(temperature),
                            None if top_k is None else int(top_k),
                            None if top_p is None else float(top_p), greedy,
                            self.repetition_penalty, self.min_new_tokens,
                            eos_token_id if self.min_new_tokens > 0 else None)
        self._sample = make_token_sampler(*self._sample_sig[:4])
        self.per_request = bool(per_request_sampling)
        # classic mode: the ctor knobs ARE the engine-wide sampler, and
        # greedy=True argmax ignores them — same silent mis-serve the
        # add_request guard closes (ADVICE r5).  NEUTRAL values pass
        # (temperature=1.0, top_p=1.0 — clients forwarding their defaults
        # are not asking for sampling).  Per-request mode is exempt: there
        # the knobs are request DEFAULTS a greedy=False request may
        # legitimately inherit.
        if not self.per_request and greedy and (
                top_k is not None
                or (top_p is not None and float(top_p) != 1.0)
                or float(temperature) != 1.0):
            raise ValueError(
                "temperature/top_k/top_p have no effect under greedy "
                "decoding (the engine default) — pass greedy=False to "
                "sample, or drop the knobs")
        if self.per_request:
            # sampler config becomes per-slot DATA (S-row planes, traced
            # operands): the ctor args are the defaults a request may
            # override per call — matching generate()'s per-call contract —
            # and the compiled program count stays mode-wide, not
            # config-wide.  Presence tracking is always on (any request
            # may carry a penalty).
            self._track = True
            self._row_sample = make_row_sampler()
            self._plane_defaults = (
                float(temperature),
                0 if top_k is None else int(top_k),
                2.0 if top_p is None else float(top_p),
                bool(greedy), self.repetition_penalty,
                self.min_new_tokens,
                -1 if eos_token_id is None else int(eos_token_id))
            self._r_temp = np.ones(self.S, np.float32)
            self._r_topk = np.zeros(self.S, np.int32)
            self._r_topp = np.full(self.S, 2.0, np.float32)
            self._r_greedy = np.ones(self.S, bool)
            self._r_rp = np.ones(self.S, np.float32)
            self._r_minnew = np.zeros(self.S, np.int32)
            self._r_eos = np.full(self.S, -1, np.int32)
        self._presence = (jnp.zeros((self.S, c.vocab_size), bool)
                          if self._track else None)

        self.mesh = mesh
        if mesh is None:
            self.caches = self._alloc_caches()
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .distributed.spmd import build_param_specs
            specs = build_param_specs(params, mesh, layer=model)
            self.params = {name: jax.device_put(
                v, NamedSharding(mesh, specs[name]))
                for name, v in params.items()}
            nh = c.num_attention_heads
            mp = mesh.shape.get("model", 1)
            shard_heads = mp > 1 and nh % mp == 0
            if mp > 1 and not shard_heads:
                import warnings
                warnings.warn(
                    f"num_attention_heads ({nh}) is not divisible by the "
                    f"model axis ({mp}): the KV cache falls back to full "
                    f"replication — per-device memory is {mp}x the "
                    f"sharded size", UserWarning)

            def leaf_spec(leaf):
                # heads is dim 3 of both the (L,S,T,nh,hd) value plane and
                # the (L,S,T,nh) int8 scale plane
                if not shard_heads:
                    return NamedSharding(mesh, P())
                entries = [None] * leaf.ndim
                entries[3] = "model"
                return NamedSharding(mesh, P(*entries))

            # allocate the cache SHARDED from the start — a transient
            # replicated (L, S, max_len, nh, hd) buffer on one device is
            # exactly the allocation TP serving exists to avoid
            shapes = jax.eval_shape(
                lambda: model.init_cache(self.S, self.max_len))
            # tpulint: disable=jit-in-hot-loop(one-shot sharded alloc at engine construction, never on the request path)
            self.caches = jax.jit(
                lambda: model.init_cache(self.S, self.max_len),
                out_shardings=jax.tree.map(leaf_spec, shapes))()
        # per-slot host state
        self._slot_req: List[Optional[Request]] = [None] * self.S
        self._t = np.zeros(self.S, np.int32)         # next physical slot
        self._pad = np.zeros(self.S, np.int32)       # left-pad length
        self._tok = np.zeros(self.S, np.int32)       # last sampled token
        self._active = np.zeros(self.S, bool)
        self._filling: Dict[int, dict] = {}          # slot -> chunked state

        self._queue: List[Request] = []
        self._finished: Dict[int, List[int]] = {}
        self._ids = itertools.count()
        # observability: a PRIVATE registry per engine (concurrent engines
        # must not alias counters) feeding metrics()/prometheus_text();
        # plain ints for the compile counters (they sit on the program-fetch
        # path and need no lock under the GIL)
        self.tracer = tracer
        self._stats = StatRegistry()
        self._started = time.monotonic()
        self._compile_hits = 0
        self._compile_misses = 0
        self._tick_note: Dict[str, object] = {}
        self._memory = None          # telemetry_memory.MemoryLedger

    def _alloc_caches(self):
        """Cache storage seam: the contiguous engine allocates one
        (L, S, max_len, nh, hd) row per slot; the paged subclass replaces
        this with a block pool + tables (serving_paged.py)."""
        return self.model.init_cache(self.S, self.max_len)

    # ---------------------------------------------------------- programs --

    @property
    def _sig(self):
        """Program-cache signature: engines with identical shapes and
        sampler config share compiled programs via the MODEL (the
        _gen_program pattern) — constructing a fresh engine per request
        wave must not recompile.  In per-request mode the sampler config is
        DATA (planes), so the signature carries only the mode marker —
        engines with different defaults share programs."""
        samp = ("perreq",) if self.per_request else self._sample_sig
        return (self.S, self.max_len, self.ticks_per_sync, samp)

    def _plane_operands(self):
        """The per-slot sampling planes as one traced operand (empty tuple
        in classic mode — a pytree with no leaves, so program signatures
        stay uniform across modes)."""
        if not self.per_request:
            return ()
        return (jnp.asarray(self._r_temp), jnp.asarray(self._r_topk),
                jnp.asarray(self._r_topp), jnp.asarray(self._r_greedy),
                jnp.asarray(self._r_rp), jnp.asarray(self._r_minnew),
                jnp.asarray(self._r_eos))

    def _cached_prog(self, cache_key, build):
        """Model-level compiled-program cache (see _sig), instrumented:
        every fetch counts a hit or miss, and with a tracer attached a
        miss's first dispatch is wall-timed — recompile storms become
        visible, warnable events instead of silent bench sinkholes."""
        progs = self.model.__dict__.setdefault("_serving_programs", {})
        if cache_key in progs:
            return self._note_prog(cache_key, True, progs[cache_key])
        run = build()
        # the BARE program goes in the model-lifetime cache; only the
        # engine-local return is timing-wrapped — a wrapper in the cache
        # would pin this engine's tracer for the model's lifetime and
        # misroute a later engine's first dispatch to it
        progs[cache_key] = run
        return self._note_prog(cache_key, False, run)

    def _note_prog(self, key, hit: bool, run=None):
        """Compile-cache accounting: bump the engine counters (always —
        two lock-free int adds), and with a tracer attached emit a compile
        event; a miss returns ``run`` wrapped so its first dispatch
        reports the compile wall time.  With ``tracer.attribute_cost``
        the first dispatch additionally records the program's XLA
        cost-analysis FLOPs/bytes (digest-cached process-wide) — on
        misses AND on model-cache hits whose label has no cost yet (a
        fresh engine over a warm model still gets MFU attribution)."""
        if hit:
            self._compile_hits += 1
        else:
            self._compile_misses += 1
        tr = self.tracer
        if tr is None:
            return run
        label = program_label(key)
        self._tick_note.setdefault("programs", []).append(label)
        name = type(self).__name__
        if hit:
            tr.compile_event(name, key, True)
            if tr.attribute_cost and not tr.has_cost(label):
                # a zero sentinel on probe failure stops re-probing the
                # same label on every later fetch
                return _timed_first_dispatch(
                    run, lambda dt, a, kw: tr.record_cost(
                        label, _program_cost(run, a, kw)
                        or {"flops": 0.0, "bytes": 0.0}))
            return run
        self._tick_note["compiles"] = \
            self._tick_note.get("compiles", 0) + 1

        def report(dt, a, kw):
            cost = (_program_cost(run, a, kw)
                    if tr.attribute_cost else None)
            tr.compile_event(name, key, False, dt, cost=cost)

        return _timed_first_dispatch(run, report)

    def attach_ledger(self, ledger):
        """Route this engine's wall-clock into a ``telemetry_ledger
        .RunLedger``: scheduler-tick walls feed the ``compute`` bucket and
        compile-miss walls feed ``compile``, through the attached tracer's
        event stream (``Tracer.set_ledger``) — the goodput accounting for
        a serving process.  Requires a ``tracer=``; the ledger consumes
        tracer events rather than adding a second instrumentation layer."""
        if self.tracer is None:
            raise ValueError(
                "attach_ledger needs a tracer: construct the engine with "
                "tracer=Tracer() — the ledger consumes its event stream")
        self.tracer.set_ledger(ledger)
        return ledger

    def attach_memory(self, ledger):
        """Register this engine's device arrays with a
        ``telemetry_memory.MemoryLedger``: params → the ``params`` pool,
        the KV caches → ``kv_pages`` (the hbm tier of the census).
        ``metrics()`` then carries ``memory_device_bytes`` /
        ``memory_host_bytes``.  Tick programs rebuild the caches
        functionally, so their registration goes stale between ticks —
        call :meth:`refresh_memory` before a census (the bench/ops
        pattern); steady-state ticks stay untouched."""
        self._memory = ledger
        if self.tracer is not None and getattr(ledger, "_tracer", None) \
                is None:
            ledger.set_tracer(self.tracer)
        self.refresh_memory()
        return ledger

    def refresh_memory(self):
        """Re-register params + current KV caches with the attached
        memory ledger (no-op without one — one attribute check)."""
        ml = self._memory
        if ml is None:
            return
        ml.register_tree("params", self.params,
                         name=f"engine{id(self)}.params")
        caches = getattr(self, "caches", None)
        if caches is not None:
            ml.register_tree("kv_pages", caches,
                             name=f"engine{id(self)}.kv")

    def _note(self, key: str, value=1):
        """Accumulate one per-tick telemetry field (no-op when tracing is
        off — a single attribute check)."""
        if self.tracer is None:
            return
        self._tick_note[key] = self._tick_note.get(key, 0) + value

    def _first_token_tail(self):
        """The first-token sampling sequence (penalty → EOS window → draw →
        presence update) shared by whole-bucket prefill and the last
        prefill segment — ONE copy, so the two admission paths cannot
        drift (test_chunked_prefill_matches_whole_prefill pins it)."""
        sample = self._sample
        track = self._track
        rp, min_new, eos = self._sample_sig[4:]
        model = self.model
        if self.per_request:
            row_sample = self._row_sample

            def tail(params, h_last, presence, slot, key, planes=()):
                temp, topk, topp, greedy, rpv, mnv, eosv = planes
                l2 = model.decode_logits(params, h_last)[:, -1]
                l2 = apply_repetition_penalty(l2, presence[slot][None],
                                              rpv[slot][None])
                # first token: emitted count is 0, window open iff mn > 0
                l2 = suppress_eos_rows(l2, eosv[slot][None],
                                       (mnv[slot] > 0)[None])
                tok = row_sample(l2[:, None, :], key, temp[slot][None],
                                 topk[slot][None], topp[slot][None],
                                 greedy[slot][None])[0]
                presence = presence.at[slot, tok].set(True)
                return tok, presence
            return tail

        def tail(params, h_last, presence, slot, key, planes=()):
            l2 = model.decode_logits(params, h_last)[:, -1]
            if track:
                l2 = apply_repetition_penalty(l2, presence[slot][None], rp)
            if min_new > 0:
                l2 = suppress_eos(l2, eos, jnp.bool_(True))  # emitted 0
            tok = sample(l2[:, None, :], key)[0]
            if track:
                presence = presence.at[slot, tok].set(True)
            return tok, presence
        return tail

    def _prefill_prog(self, P: int):
        """Prefill ONE request (left-padded to bucket length P) directly
        into slot ``slot`` of the global cache; returns the first token."""
        return self._cached_prog(("prefill", P, self._sig),
                                 lambda: self._build_prefill(P))

    def _build_prefill(self, P: int):
        model = self.model
        track = self._track
        V = model.config.vocab_size
        tail = self._first_token_tail()

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def run(params, big_ck, big_cv, ids, pad_len, slot, key, presence,
                planes):
            h, (ck, cv) = model.prefill(params, ids, P,
                                        pad_lens=pad_len[None])

            put = _slot_write(slot)
            big_ck = jax.tree.map(put, big_ck, ck)
            big_cv = jax.tree.map(put, big_cv, cv)
            if track:
                # reset + seed the slot's presence row from the prompt
                row = seed_presence(ids, V, pad_len[None])
                presence = jax.lax.dynamic_update_slice(
                    presence, row, (slot, 0))
            tok, presence = tail(params, h[:, -1:], presence, slot, key,
                                 planes)
            return big_ck, big_cv, tok, presence

        return run

    def _seg_prog(self, seg: int, first: bool, last: bool):
        """One prefill SEGMENT for one slot: embed ``seg`` prompt tokens at
        [t0, t0+seg), write the slot's cache region via the chunk decode
        path (cached_attention's k-query form — the same machinery as
        speculative verification), and on the last segment sample the first
        token.  Only the slot's cache row is computed on (sliced out and
        written back), so a segment costs B=1 work, not B=S."""
        return self._cached_prog(
            ("seg", seg, first, last, self._sig),
            lambda: self._build_seg(seg, first, last))

    def _build_seg(self, seg: int, first: bool, last: bool):
        model = self.model
        track = self._track
        V = model.config.vocab_size
        tail = self._first_token_tail()

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def run(params, big_ck, big_cv, toks, t0, pad, slot, presence, key,
                planes):
            take = lambda a: jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=1)
            ck_s = jax.tree.map(take, big_ck)
            cv_s = jax.tree.map(take, big_cv)
            h = model._embed_chunk(params, toks[0], t0, pad_lens=pad[None])
            h, (ck_s, cv_s) = model.decode_step(params, h, (ck_s, cv_s), t0,
                                                pad_lens=pad[None])

            put = _slot_write(slot)
            big_ck = jax.tree.map(put, big_ck, ck_s)
            big_cv = jax.tree.map(put, big_cv, cv_s)
            if track:
                if first:
                    presence = jax.lax.dynamic_update_slice(
                        presence, jnp.zeros((1, V), bool), (slot, 0))
                valid = t0 + jnp.arange(seg) >= pad     # pads: segment 0
                row = presence[slot].at[toks[0]].max(valid)
                presence = jax.lax.dynamic_update_slice(
                    presence, row[None], (slot, 0))
            tok = jnp.int32(0)
            if last:
                tok, presence = tail(params, h[:, -1:], presence, slot, key,
                                     planes)
            return big_ck, big_cv, tok, presence

        return run

    def _decode_prog_all(self):
        """``ticks_per_sync`` decode ticks over all S slots (per-row cache
        clocks), one host sync: returns the (k, S) token block."""
        return self._cached_prog(("decode", self._sig), self._build_decode)

    def _make_decode_tick(self):
        """One decode tick over all S slots (embed → decode_step → process →
        sample → presence), shared by the contiguous and paged decode
        programs so the scheduling semantics cannot drift between cache
        layouts.  ``caches`` inside the tick is whatever layout the calling
        program scans over (the paged program passes the gathered logical
        view)."""
        model = self.model
        sample = self._sample
        track = self._track
        rp, min_new, eos = self._sample_sig[4:]
        S = self.S
        per_request = self.per_request
        row_sample = self._row_sample if per_request else None

        def tick(carry, i, params, ts, pads, active, emitted0, planes=()):
            big_ck, big_cv, tok, key, presence = carry
            h = model._embed_one(params, tok, ts + i, pad_lens=pads)
            h, (big_ck, big_cv) = model.decode_step(
                params, h, (big_ck, big_cv), ts + i, pad_lens=pads)
            key, sub = jax.random.split(key)
            l2 = model.decode_logits(params, h)[:, -1]
            if per_request:
                temp, topk, topp, greedy, rpv, mnv, eosv = planes
                l2 = apply_repetition_penalty(l2, presence, rpv)
                l2 = suppress_eos_rows(l2, eosv, emitted0 + i < mnv)
                ntok = row_sample(l2[:, None, :], sub, temp, topk, topp,
                                  greedy)
            else:
                if track:
                    l2 = apply_repetition_penalty(l2, presence, rp)
                if min_new > 0:
                    # per-row window: each request's own emission count
                    l2 = suppress_eos(l2, eos, emitted0 + i < min_new)
                ntok = sample(l2[:, None, :], sub)
            # inactive slots carry their token unchanged (their stale
            # cache writes are never read — see module docstring)
            ntok = jnp.where(active, ntok, tok)
            if track:
                # bool max == set-only-where-active: an INACTIVE slot's
                # ntok is a stale carried token (previous occupant, or a
                # chunk-filling request's segment-0-reset row) — marking
                # it would poison the next occupant's penalty plane
                presence = presence.at[jnp.arange(S), ntok].max(active)
            return (big_ck, big_cv, ntok, key, presence), ntok

        return tick

    def _build_decode(self):
        k_ticks = self.ticks_per_sync
        tick = self._make_decode_tick()

        @partial(jax.jit, donate_argnums=(1, 2, 8))
        def run(params, big_ck, big_cv, toks, ts, pads, active, key,
                presence, emitted0, planes):
            (big_ck, big_cv, _, _, presence), toks_out = jax.lax.scan(
                lambda c, i: tick(c, i, params, ts, pads, active, emitted0,
                                  planes),
                (big_ck, big_cv, toks, key, presence),
                jnp.arange(k_ticks))
            return big_ck, big_cv, toks_out, presence      # toks (k, S)

        return run

    # ------------------------------------------------------------- warmup --

    def compile_grid(self) -> List[str]:
        """Labels of every program family this engine can dispatch — the
        declared compile grid the AOT warmup planner precompiles
        (jit/aot.py; docs/COMPILATION.md)."""
        return [t.label for t in self._warmup_tasks()]

    def warmup(self, cache_dir=None, max_workers: int = 1,
               block: bool = True):
        """Precompile the engine's full program grid BEFORE traffic, so no
        request ever pays an XLA compile stall on the serving path.

        ``cache_dir``: also wires jax's persistent compilation cache there,
        making the compiles durable across processes — a later engine (or
        restart) warming against the same directory re-traces but skips
        XLA, and its compile events carry ``provenance: disk``.
        ``block=False`` runs on a background thread and returns the report
        Future (``jit.aot.warmup_async``); requests admitted mid-warmup
        simply compile what they need first.

        Each task dispatches against freshly allocated scratch caches
        (donated and freed immediately), a constant key, and zeroed
        metadata: live engine state, the sampling key stream, and request
        outputs are untouched — a warmed engine serves token-for-token
        what an unwarmed one would.  Transient memory: each IN-FLIGHT
        task holds one scratch cache allocation, so peak extra HBM is
        ``max_workers`` cache copies on top of the live cache — keep the
        default ``max_workers=1`` on memory-tight configs.  With a tracer
        attached the run sits in an ``expected_compiles`` window (compile
        events tagged, storm warning ignores them)."""
        if self.mesh is not None:
            # scratch caches come from _alloc_caches (host layout); the TP
            # engine's live caches are mesh-sharded, so a scratch dispatch
            # would compile a DIFFERENT program than serving uses — worse
            # than no warmup (it hides the stall behind a false green)
            raise NotImplementedError(
                "warmup v1 is single-mesh; TP serving engines compile on "
                "first dispatch (persistent-cache reuse still applies via "
                "jit.aot.enable_persistent_compilation_cache)")
        from .jit.aot import run_warmup, warmup_async
        tasks = self._warmup_tasks()
        kw = dict(tracer=self.tracer, cache_dir=cache_dir,
                  max_workers=max_workers)
        if block:
            return run_warmup(tasks, **kw)
        return warmup_async(tasks, **kw)

    def _prefill_seg_tasks(self):
        """Prefill-bucket + chunked-seg warmup tasks — ONE enumeration
        shared by the contiguous and paged grids (the paged engine
        overrides only the dispatch helpers and its decode family), so
        the two engines' seg-variant sets cannot drift."""
        from .jit.aot import WarmupTask
        tasks = []
        chunk = self.prefill_chunk
        for P in self.buckets:
            if chunk is not None and P > chunk:
                continue                  # chunked buckets use seg programs
            tasks.append(WarmupTask(f"prefill:{P}",
                                    partial(self._warmup_prefill, P)))
        if chunk is not None:
            combos = sorted({(i == 0, i == P // chunk - 1)
                             for P in self.buckets if P > chunk
                             for i in range(P // chunk)})
            for first, last in combos:
                tasks.append(WarmupTask(
                    f"seg:{chunk}:{int(first)}{int(last)}",
                    partial(self._warmup_seg, first, last)))
        return tasks

    def _warmup_tasks(self):
        from .jit.aot import WarmupTask
        tasks = self._prefill_seg_tasks()
        tasks.append(WarmupTask("decode", self._warmup_decode))
        return tasks

    def _scratch_presence(self):
        return None if self._presence is None \
            else jnp.zeros_like(self._presence)

    @staticmethod
    def _warmup_key():
        # constant: warmup must not advance the engine's sampling stream
        # (a warmed sampled engine draws the same tokens as an unwarmed one)
        return jax.random.key(0)

    def _warmup_prefill(self, P: int):
        run = self._prefill_prog(P)
        ck, cv = self._alloc_caches()
        jax.block_until_ready(run(
            self.params, ck, cv, jnp.zeros((1, P), jnp.int32),
            jnp.int32(0), jnp.int32(0), self._warmup_key(),
            self._scratch_presence(), self._plane_operands()))

    def _warmup_seg(self, first: bool, last: bool):
        seg = self.prefill_chunk
        run = self._seg_prog(seg, first, last)
        ck, cv = self._alloc_caches()
        jax.block_until_ready(run(
            self.params, ck, cv, jnp.zeros((1, seg), jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(0),
            self._scratch_presence(), self._warmup_key(),
            self._plane_operands()))

    def _warmup_decode(self):
        run = self._decode_prog_all()
        ck, cv = self._alloc_caches()
        z = jnp.zeros(self.S, jnp.int32)
        jax.block_until_ready(run(
            self.params, ck, cv, z, z, z, jnp.zeros(self.S, bool),
            self._warmup_key(), self._scratch_presence(), z,
            self._plane_operands()))

    # --------------------------------------------------------- scheduling --

    def add_request(self, prompt, max_new_tokens: int,
                    on_token=None, trace_ctx=None, **sampling) -> int:
        """Queue a prompt; returns the request id.  Admission happens inside
        ``step()`` whenever a slot is free.

        ``trace_ctx``: optional ``telemetry.TraceContext`` propagated by a
        caller that minted the request's end-to-end trace (the gateway's
        dispatch path).  Host-side metadata only — it binds the engine
        rid to the trace in the attached tracer so every request-timeline
        event carries the shared trace_id; compiled programs and their
        cache keys are identical with or without one.

        ``on_token(request_id, token, done)``: optional streaming callback,
        invoked on the host as each token is accepted (chunked/speculative
        modes deliver a burst per sync — ordering within a request is
        guaranteed, across requests it follows slot order).  A
        ``cancel(request_id)`` ends the stream with ONE terminal
        ``on_token(request_id, None, True)`` call — ``token is None`` with
        ``done=True`` is the documented clean end-of-stream (the paged
        engines' preemption replay signal is the ``done=False`` variant).

        With ``per_request_sampling=True`` the engine accepts the
        generate()-style per-call knobs here — ``temperature``, ``top_k``,
        ``top_p``, ``greedy``, ``repetition_penalty``, ``min_new_tokens``,
        ``eos_token_id`` — each defaulting to the engine's constructor
        value.  The configs ride per-slot data planes: any mixture shares
        ONE compiled decode program."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new_tokens) <= 0:
            # generate() returns an empty array here; a scheduler admitting
            # the request would still emit the prefill token, silently
            # over-generating — refuse instead
            raise ValueError("max_new_tokens must be >= 1")
        P = select_bucket(len(prompt), self.buckets)
        need = self._positions_needed(P, int(max_new_tokens))
        if need > self.max_len:
            raise ValueError(
                f"bucketed prompt ({len(prompt)} -> bucket {P}) needs "
                f"{need} cache positions for max_new_tokens="
                f"{max_new_tokens}; exceeds max_len ({self.max_len})")
        req = Request(next(self._ids), prompt, max_new_tokens)
        req.sampling = self._resolve_sampling(sampling)
        req.on_token = on_token
        self._queue.append(req)
        if self.tracer is not None:
            if trace_ctx is not None:
                self.tracer.bind_trace(req.id, trace_ctx)
            self.tracer.request_event(req.id, "queued",
                                      prompt_len=len(prompt))
        return req.id

    _SAMPLING_KEYS = ("temperature", "top_k", "top_p", "greedy",
                      "repetition_penalty", "min_new_tokens",
                      "eos_token_id")

    def _resolve_sampling(self, overrides):
        """Merge per-request overrides onto the engine defaults and
        validate; returns the plane-encoded tuple (or None in classic
        mode, where any override is an error)."""
        unknown = set(overrides) - set(self._SAMPLING_KEYS)
        if unknown:
            raise TypeError(f"unknown add_request kwargs: {sorted(unknown)}")
        given = {k: v for k, v in overrides.items() if v is not None}
        if not self.per_request:
            if given:
                raise ValueError(
                    f"per-request sampling params {sorted(given)} need "
                    f"per_request_sampling=True")
            return None
        V = self.model.config.vocab_size
        t, k, p, g, rp, mn, eos = self._plane_defaults
        if "temperature" in given:
            t = float(given["temperature"])
            if t <= 0:
                raise ValueError("temperature must be > 0 (use greedy=True "
                                 "for deterministic decoding)")
        if "top_k" in given:
            k = int(given["top_k"])
            validate_sampler_args(V, k, None, True, None)
        if "top_p" in given:
            p = float(given["top_p"])
            validate_sampler_args(V, None, p, True, None)
        if "greedy" in given:
            g = bool(given["greedy"])
        if "repetition_penalty" in given:
            rp = float(given["repetition_penalty"])
            if rp <= 0:
                raise ValueError("repetition_penalty must be > 0")
        if "min_new_tokens" in given:
            mn = int(given["min_new_tokens"])
            if mn < 0:
                raise ValueError("min_new_tokens must be >= 0")
        if "eos_token_id" in given:
            eos = int(given["eos_token_id"])
            if not 0 <= eos < V:
                raise ValueError(f"eos_token_id {eos} outside vocab "
                                 f"(size {V})")
        if mn > 0 and eos < 0:
            raise ValueError("min_new_tokens needs an eos_token_id "
                             "(engine default or per-request)")
        # sampling-only knobs are argmax-inert while the effective greedy
        # flag is True — add_request(p, n, temperature=0.8) would silently
        # decode greedy (ADVICE r5); fail loudly instead of mis-serving.
        # NEUTRAL values pass (temperature=1.0, top_p=1.0): clients that
        # always forward their defaults are not asking for sampling (the
        # ctor guard draws the same line)
        if g and (("temperature" in given and t != 1.0)
                  or "top_k" in given
                  or ("top_p" in given and p != 1.0)):
            raise ValueError(
                "temperature/top_k/top_p have no effect under greedy "
                "decoding — pass greedy=False with them (or construct the "
                "engine with greedy=False)")
        return (t, k, p, g, rp, mn, eos)

    def _positions_needed(self, P: int, mnt: int) -> int:
        """Worst-case cache positions a request occupies — the bucket plus
        CHUNK-ROUNDED decode: the first token comes from prefill (no decode
        position), the remaining budget-1 tokens consume ceil((budget-1)/k)
        * k positions (decode advances k ticks per sync; pad slots occupy
        physical positions).  The speculative engine overrides this with
        its over-proposal arithmetic."""
        k = self.ticks_per_sync
        return P + -(-(mnt - 1) // k) * k

    def pending(self) -> bool:
        return bool(self._queue) or bool(self._active.any()) \
            or bool(self._filling)

    # ------------------------------------------------------ prefix index --

    #: engines without a prefix cache answer the routing plane honestly
    prefix_caching = False

    def prefix_index(self) -> Dict[str, str]:
        """PUBLIC prefix-cache view: ``{chain_hex: tier}`` for every
        resident prefix page (``"hbm"`` here; the paged engines merge
        their attached :class:`~paddle_tpu.kv_store.TieredKVStore`'s
        ``"dram"``/``"disk"`` tiers under it).  The gateway's
        fleet-wide ``prefix_index()`` and the ops ``/kvstore`` view read
        this instead of reaching into engine internals.  Empty for
        engines without prefix caching."""
        return {}

    def prefix_match(self, prompt) -> Dict[str, Any]:
        """PUBLIC tier-aware prefix-affinity read for one prompt:
        ``{"hbm": leading blocks resident in HBM, "total": leading
        blocks resident in ANY tier, "tiers": per-block tier labels}``.
        A pure read — no LRU touch, no pinning, no restore (admission
        does those).  The gateway's router scores replicas with this:
        a deep lower-tier hit (restorable, no recompute) outranks a
        shallow HBM hit."""
        return {"hbm": 0, "total": 0, "tiers": []}

    def pop_finished(self) -> Dict[int, List[int]]:
        out, self._finished = self._finished, {}
        return out

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _free_slots(self):
        return [s for s in range(self.S)
                if not self._active[s] and s not in self._filling]

    def _admit(self):
        free = self._free_slots()
        while self._queue and free:
            slot = free.pop(0)
            req = self._queue.pop(0)
            P = select_bucket(len(req.prompt), self.buckets)
            pad = P - len(req.prompt)
            ids = [0] * pad + req.prompt
            if self.prefill_chunk is not None and P > self.prefill_chunk:
                # chunked admission: segments run one per scheduler round,
                # interleaved with everyone else's decode.  PARK the slot's
                # decode clock in the strip above every chunked bucket:
                # the batched decode program stale-writes EVERY row at its
                # clock each tick (inactive ones included), and unlike
                # whole-bucket prefill — which overwrites [0, P) after any
                # stale write — segments land progressively, so a stale
                # write at the old clock (0 for a fresh slot) would corrupt
                # already-filled prompt positions.  The parking strip is
                # overwritten by the occupant's own decode before it can
                # ever be read (write-before-read induction).
                self._set_planes(slot, req)
                self._t[slot] = self.max_len - self.ticks_per_sync
                self._filling[slot] = {"req": req, "ids": ids, "pad": pad,
                                       "P": P, "seg": 0,
                                       "nseg": P // self.prefill_chunk}
                continue
            self._set_planes(slot, req)
            run = self._prefill_prog(P)
            ck, cv, tok0, self._presence = run(
                self.params, self.caches[0], self.caches[1],
                jnp.asarray([ids], jnp.int32), jnp.int32(pad),
                jnp.int32(slot), self._next_key(), self._presence,
                self._plane_operands())
            self.caches = (ck, cv)
            self._note("prefill_tokens", P)
            self._activate(slot, req, P, pad, int(tok0))

    def _set_planes(self, slot, req):
        """Write the request's effective sampler config into the slot's
        row of the per-request planes (no-op in classic mode).  Must run
        BEFORE the admission prefill — the first token samples through the
        planes.  Doubles as the single admission choke point every engine
        passes through, so it also emits the ``admitted`` telemetry
        transition."""
        if self.tracer is not None:
            self.tracer.request_event(req.id, "admitted", slot=int(slot))
        if not self.per_request:
            return
        t, k, p, g, rp, mn, eos = req.sampling
        self._r_temp[slot] = t
        self._r_topk[slot] = k
        self._r_topp[slot] = p
        self._r_greedy[slot] = g
        self._r_rp[slot] = rp
        self._r_minnew[slot] = mn
        self._r_eos[slot] = eos

    def _activate(self, slot, req, P, pad, tok0):
        req.first_token_at = time.monotonic()   # tok0 exists: TTFT point
        if self.tracer is not None:
            self.tracer.request_event(req.id, "first_token",
                                      slot=int(slot))
        self._slot_req[slot] = req
        self._t[slot] = P
        self._pad[slot] = pad
        self._tok[slot] = tok0
        self._active[slot] = True
        self._record(slot, tok0)

    def _fill_segments(self):
        """Run ONE prefill segment for every filling slot (round-robin
        progress: a long prompt advances without stalling decode)."""
        seg = self.prefill_chunk
        for slot, st in list(self._filling.items()):
            i, first = st["seg"], st["seg"] == 0
            last = i == st["nseg"] - 1
            toks = jnp.asarray([st["ids"][i * seg:(i + 1) * seg]], jnp.int32)
            run = self._seg_prog(seg, first, last)
            ck, cv, tok0, self._presence = run(
                self.params, self.caches[0], self.caches[1], toks,
                jnp.int32(i * seg), jnp.int32(st["pad"]), jnp.int32(slot),
                self._presence, self._next_key(), self._plane_operands())
            self.caches = (ck, cv)
            self._note("prefill_tokens", seg)
            if last:
                del self._filling[slot]
                self._activate(slot, st["req"], st["P"], st["pad"],
                               int(tok0))
            else:
                st["seg"] += 1

    def _record(self, slot: int, tok: int):
        """Append a token to the slot's request; retire on EOS/budget."""
        req = self._slot_req[slot]
        req.generated.append(tok)
        if self.tracer is not None:
            self.tracer.request_event(req.id, "token", token=int(tok))
        eos = (req.sampling[6] if self.per_request else self.eos_token_id)
        hit_eos = (eos is not None and eos >= 0 and tok == eos)
        done = len(req.generated) >= req.max_new_tokens or hit_eos
        if req.on_token is not None:
            try:
                req.on_token(req.id, tok, done)
            except Exception:  # noqa: BLE001 — a user callback must not
                # desync host state mid-block (tokens for later slots in
                # this sync would be silently dropped); log and continue
                logging.getLogger(__name__).exception(
                    "on_token callback failed for request %d", req.id)
        # the callback may have cancel()ed this very request (reentrant
        # consumer): the slot is already released — nothing left to retire
        if done and self._slot_req[slot] is not None:
            self._retire(slot)

    def _retire(self, slot: int):
        req = self._slot_req[slot]
        req.done = True
        req.finished_at = time.monotonic()
        self._finished[req.id] = list(req.generated)
        self._slot_req[slot] = None
        self._active[slot] = False
        n = len(req.generated)
        stat_add("serving_requests_finished")
        stat_add("serving_tokens_emitted", n)
        s = self._stats
        s.add("requests_finished")
        s.add("tokens_emitted", n)
        s.add("ttft_seconds_sum", req.first_token_at - req.enqueued_at)
        s.add("latency_seconds_sum", req.finished_at - req.enqueued_at)
        if self.tracer is not None:
            self.tracer.request_event(req.id, "retired", tokens=n)

    def cancel(self, rid: int) -> bool:
        """Cancel one in-flight request and release every resource it holds.

        Works at ANY lifecycle stage — still queued, mid-(chunked-)prefill,
        or actively decoding — and is pure host bookkeeping (no device
        program runs): the slot frees for the next admission, the paged
        engines additionally release the slot's KV blocks and prefix-cache
        pins (``_release_cancelled_slot``), and per-request sampling rows
        reset to the engine defaults.  Cancelled requests never appear in
        ``pop_finished()``; a streaming consumer gets ONE terminal
        ``on_token(rid, None, True)`` call — the documented clean
        end-of-stream (``done=True``, vs the preemption replay signal's
        ``done=False``).  Returns True iff the request was found in flight;
        False means an unknown rid or an already-finished request (the
        caller raced retirement — its tokens are in ``pop_finished()``).

        The slot's stale cache/presence contents need no device work: the
        next occupant's admission prefill rewrites both before anything
        reads them (the same write-before-read induction inactive slots
        rely on — module docstring)."""
        for i, req in enumerate(self._queue):
            if req.id == rid:
                del self._queue[i]
                self._finalize_cancel(req)
                return True
        for slot, st in list(self._filling.items()):
            if st["req"].id == rid:
                del self._filling[slot]
                self._release_cancelled_slot(slot)
                self._finalize_cancel(st["req"])
                return True
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.id == rid:
                self._slot_req[slot] = None
                self._active[slot] = False
                self._release_cancelled_slot(slot)
                self._finalize_cancel(req)
                return True
        return False

    def _release_cancelled_slot(self, slot: int):
        """Free the per-slot resources a cancelled occupant held (seam:
        the paged engines add block + prefix-pin release)."""
        if self.per_request:
            t, k, p, g, rp, mn, eos = self._plane_defaults
            self._r_temp[slot] = t
            self._r_topk[slot] = k
            self._r_topp[slot] = p
            self._r_greedy[slot] = g
            self._r_rp[slot] = rp
            self._r_minnew[slot] = mn
            self._r_eos[slot] = eos

    def _finalize_cancel(self, req: Request):
        """Terminal bookkeeping shared by every cancel path: counters, the
        ``cancelled`` telemetry transition, and the clean end-of-stream
        signal."""
        req.done = True
        req.finished_at = time.monotonic()
        self._stats.add("requests_cancelled")
        stat_add("serving_requests_cancelled")
        if self.tracer is not None:
            self.tracer.request_event(req.id, "cancelled",
                                      tokens=len(req.generated))
        if req.on_token is not None:
            try:
                req.on_token(req.id, None, True)   # terminal end-of-stream
            except Exception:  # noqa: BLE001 — same contract as _record:
                # a user callback must not desync the scheduler
                logging.getLogger(__name__).exception(
                    "on_token cancel signal failed for request %d", req.id)

    _TICK_COUNTERS = ("tokens_emitted", "requests_finished")

    def step(self):
        """One scheduler round (each engine's ``_step_impl`` documents its
        semantics).  With a tracer attached the round is bracketed by tick
        telemetry — host wall time, queue depth, counter deltas, packed
        rows, program labels; with ``tracer=None`` (default) this wrapper
        is ONE attribute check and a tail call: no event allocation, no
        tracer lock, no extra operands anywhere near a compiled program.

        An exception escaping ``_step_impl`` is SURFACED before it
        propagates — the ``step_errors`` counter ticks and (with a
        tracer) an ``engine_error`` event lands in the ring — so a
        replica that dies mid-tick leaves evidence in the observability
        plane even when its caller (the gateway's step isolation, a bare
        serving loop) swallows or crashes on the re-raise."""
        tr = self.tracer
        if tr is None:
            try:
                return self._step_impl()
            except Exception:
                self._stats.add("step_errors")
                raise
        t0 = time.perf_counter()
        self._tick_note = {}
        s = self._stats
        base = {k: s.value(k) for k in self._TICK_COUNTERS}
        try:
            return self._step_impl()
        except Exception as e:
            self._stats.add("step_errors")
            tr.emit("engine_error", what="step_error",
                    engine=type(self).__name__, error=repr(e))
            raise
        finally:
            fields = {k: s.value(k) - base[k] for k in self._TICK_COUNTERS}
            fields.update(self._tick_gauges())
            fields.update(self._tick_note)
            self._tick_note = {}
            tr.tick(type(self).__name__, time.perf_counter() - t0,
                    queue_depth=len(self._queue),
                    active=int(self._active.sum()),
                    filling=len(self._filling), **fields)

    def _tick_gauges(self) -> Dict[str, float]:
        """Instantaneous per-tick gauges (subclass hook; only consulted
        when tracing is on)."""
        return {}

    def _step_impl(self):
        """One scheduler round: admit waiting requests into free slots, then
        run ``ticks_per_sync`` batched decode ticks and retire finished
        requests from the returned token block."""
        self._admit()
        if self._filling:
            self._fill_segments()
        if not self._active.any():
            return
        res = self._run_decode()
        if res is None:
            return
        active_before, blk = res                   # blk (k, S)
        for slot in np.flatnonzero(active_before):
            for j in range(self.ticks_per_sync):
                if not self._active[slot]:
                    break  # retired mid-chunk: discard the chunk's tail
                self._t[slot] += 1
                self._tok[slot] = blk[j, slot]
                self._record(int(slot), int(blk[j, slot]))
            # room is a CHUNK-boundary concern: a surviving slot must fit a
            # whole next chunk.  Admission-validated budgets always do; this
            # is the safety net against inconsistent slot state, truncating
            # rather than writing past the cache.
            if self._active[slot] and \
                    int(self._t[slot]) + self.ticks_per_sync > self.max_len:
                self._retire(int(slot))

    def _prepare_decode(self) -> bool:
        """Pre-sync hook: the paged subclass grows block tables here
        (preempting when the pool is dry).  False = nothing left to
        decode."""
        return True

    def _decode_extra_operands(self):
        """Extra traced operands the decode program takes after the caches
        (the paged subclass passes its block table)."""
        return ()

    def _run_decode(self):
        """One ``ticks_per_sync`` decode sync over the engine's cache
        storage; returns (active_before, (k, S) token block) or None if no
        slot could decode."""
        if not self._prepare_decode():
            return None
        run = self._decode_prog_all()
        active_before = self._active.copy()
        self._note("decode_rows", int(active_before.sum()))
        emitted0 = np.asarray(
            [len(r.generated) if r is not None else 0
             for r in self._slot_req], np.int32)
        ck, cv, blk, self._presence = run(
            self.params, self.caches[0], self.caches[1],
            *self._decode_extra_operands(),
            jnp.asarray(self._tok), jnp.asarray(self._t),
            jnp.asarray(self._pad), jnp.asarray(active_before),
            self._next_key(), self._presence, jnp.asarray(emitted0),
            self._plane_operands())
        self.caches = (ck, cv)
        return active_before, np.asarray(blk)

    # metrics() contract: {key: (kind, pytype)}; kind "counter" = monotonic
    # over the engine's lifetime, "gauge" = instantaneous/derived.  Keys
    # never change meaning; subclasses extend (docs/OBSERVABILITY.md).
    METRICS_SCHEMA = {
        "requests_finished": ("counter", int),
        "requests_cancelled": ("counter", int),
        "tokens_emitted": ("counter", int),
        "mean_ttft_s": ("gauge", float),
        "mean_latency_s": ("gauge", float),
        "tokens_per_sec": ("gauge", float),
        "compile_hits": ("counter", int),
        "compile_misses": ("counter", int),
        "step_errors": ("counter", int),
        # present only with attach_memory(MemoryLedger):
        "memory_device_bytes": ("gauge", float),
        "memory_host_bytes": ("gauge", float),
    }

    @classmethod
    def metrics_schema(cls) -> Dict[str, tuple]:
        """The stable ``metrics()`` schema for this engine class, merged
        over the MRO.  Every key metrics() returns appears here with its
        kind and type; conditional keys (prefix caching off) may be absent
        from a given metrics() dict but never change meaning."""
        out: Dict[str, tuple] = {}
        for klass in reversed(cls.__mro__):
            out.update(klass.__dict__.get("METRICS_SCHEMA", {}))
        return out

    def metrics(self) -> Dict[str, float]:
        """Serving observability, registry-backed (one private
        ``utils.stats.StatRegistry`` per engine — the same mechanism the
        rest of the framework counts through, exported whole by
        ``prometheus_text()``): finished-request counts, mean
        time-to-first-token (queue wait + prefill), mean request latency,
        lifetime throughput, and compile-cache hit/miss counts.  Schema:
        ``metrics_schema()``."""
        s = self._stats
        nreq = int(s.value("requests_finished"))
        n = max(nreq, 1)
        toks = int(s.value("tokens_emitted"))
        dt = max(time.monotonic() - self._started, 1e-9)
        out = {"requests_finished": nreq,
               "requests_cancelled": int(s.value("requests_cancelled")),
               "tokens_emitted": toks,
               "mean_ttft_s": float(s.value("ttft_seconds_sum")) / n,
               "mean_latency_s": float(s.value("latency_seconds_sum")) / n,
               "tokens_per_sec": toks / dt,
               "compile_hits": self._compile_hits,
               "compile_misses": self._compile_misses,
               "step_errors": int(s.value("step_errors"))}
        if self._memory is not None:
            totals = self._memory.memory_snapshot()["totals"]
            out["memory_device_bytes"] = float(totals["device_bytes"])
            out["memory_host_bytes"] = float(totals["host_bytes"])
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_serving") -> str:
        """Prometheus text exposition of this engine's registry plus the
        derived ``metrics()`` values not stored as raw registry stats,
        each typed per ``metrics_schema()`` (compile counts stay
        counters, means/throughput are gauges)."""
        raw = set(self._stats.snapshot())
        schema = self.metrics_schema()
        gauges, counters = {}, {}
        for k, v in self.metrics().items():
            if k in raw:
                continue
            (counters if schema[k][0] == "counter" else gauges)[k] = v
        return _prometheus_text(self._stats, namespace=namespace,
                                extra_gauges=gauges,
                                extra_counters=counters)

    def run_to_completion(self, max_ticks: Optional[int] = None
                          ) -> Dict[int, List[int]]:
        """Drive step() until every queued request finishes; returns
        {request_id: generated tokens}."""
        ticks = 0
        while self.pending():
            self.step()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(f"not done after {max_ticks} ticks")
        return self.pop_finished()


# The speculative engines and every paged (block-table) variant are
# defined in serving_paged.py and re-exported here LAZILY (PEP 562) so
# `paddle_tpu.serving` stays the single public serving namespace without
# a circular import (serving_paged imports this module at its top).
# `SpeculativeBatchingEngine` / `PagedSpeculativeBatchingEngine` are now
# deprecation SHIMS over the unified ragged engine: speculation runs
# inside `RaggedPagedContinuousBatchingEngine` as part of the one-
# program-per-tick ragged pack (draft_model=/draft_k= constructor args),
# so the legacy engines' separate program families are gone.
_PAGED_NAMES = ("PagedContinuousBatchingEngine",
                "PagedSpeculativeBatchingEngine",
                "RaggedPagedContinuousBatchingEngine",
                "SpeculativeBatchingEngine")
__all__ += [n for n in _PAGED_NAMES if n not in __all__]


def __getattr__(name):
    if name in _PAGED_NAMES:
        from . import serving_paged
        return getattr(serving_paged, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
