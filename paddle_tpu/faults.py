"""Deterministic, seeded fault injection for the serving control plane.

PR 11's simulation harness proved the method — a replica ``kill()`` under
a fake clock turns a minutes-long failure trajectory into a millisecond
CPU unit test — but death is only one failure mode, and hand-placed
``sim.at(t, engine.kill)`` calls don't compose into a reproducible chaos
scenario.  This module makes fault injection a first-class subsystem:

- a typed :class:`Fault` vocabulary covering the failure modes a real
  fleet sees — replica **crash** (frozen forever), **stall** (frozen for
  a window, then resumes), **slow** straggler (a latency multiplier for a
  window), transient **dispatch_error** (``add_request`` raises the
  retryable :class:`TransientDispatchError`), **warmup_fail** (the AOT
  warmup path raises), **garble** (a truncated/garbled token stream:
  the engine delivers a partial prefix, then its integrity check raises
  :class:`StreamCorruption` mid-tick), and **alloc_fail** (``step()``
  raises :class:`InjectedAllocationError`, a :class:`MemoryError` — the
  OOM shape that drives the flight recorder's memory forensics);
- a :class:`FaultPlan` — an ordered, seeded, JSON-able collection of
  faults, optionally targeted per replica name, so one plan describes a
  whole chaos scenario and the SAME plan replays the SAME scenario;
- a :class:`FaultyEngine` wrapper that injects the plan into any real
  engine's scheduling surface (``add_request`` / ``step`` / ``cancel`` /
  ``warmup``) without the engine's cooperation — it works on the five
  serving classes and on :class:`~paddle_tpu.simulation.SimEngine`
  alike, and everything else delegates through untouched.

All timing reads an injectable ``clock`` (``SimClock`` in tests, wall
clock in the ``tools/serve_gateway.py --chaos`` demo), so chaos
scenarios run deterministically through
:class:`~paddle_tpu.simulation.TrafficSim`.  Importing this module never
touches JAX — fault plans are host-side control flow only; no compiled
program changes under any fault.

The consumer of all this is the gateway's resilience layer
(``paddle_tpu.gateway.ResiliencePolicy``): circuit breakers open on the
dispatch errors injected here, retries/backoff absorb the transient
window, hedging races the slow straggler, and the stall/crash faults
drive the quarantine-replay path — docs/RESILIENCE.md walks the whole
taxonomy.

No reference counterpart: the reference snapshot serves static batches
with no failure model at all (SURVEY §2.3).
"""

from __future__ import annotations

import json
import logging
import os
import random
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["Fault", "FaultPlan", "FaultyEngine", "FAULT_KINDS",
           "TransientDispatchError", "StreamCorruption",
           "InjectedAllocationError", "FaultInjectionError",
           "torn_write", "corrupt_file"]

#: the typed fault vocabulary (docs/RESILIENCE.md taxonomy table).
#: ``torn_write``/``corrupt_file`` are FILESYSTEM faults: FaultyEngine
#: never fires them; the checkpoint layer
#: (``train_resilience.CheckpointManager``) consults the plan at save
#: time with a save-ordinal clock and applies them via the
#: :func:`torn_write`/:func:`corrupt_file` primitives below.
FAULT_KINDS = ("crash", "stall", "slow", "dispatch_error", "warmup_fail",
               "garble", "alloc_fail", "torn_write", "corrupt_file")


class FaultInjectionError(RuntimeError):
    """Base class for every injected failure — lets a test assert "this
    came from the chaos layer, not from a real bug"."""


class TransientDispatchError(FaultInjectionError):
    """A RETRYABLE dispatch failure: the engine could not admit the
    request right now (transient device hiccup, allocator pressure, a
    flaky transport), but a later attempt — here or on another replica —
    may succeed.  The gateway's resilience layer catches exactly this
    class for its retry/backoff/circuit-breaker path; anything else an
    engine raises stays a structural (non-retryable) failure."""


class InjectedAllocationError(FaultInjectionError, MemoryError):
    """An injected device-allocation failure (the OOM shape).  Raised
    from ``step()`` BEFORE the inner engine runs — the tick's allocation
    "failed", no tokens moved.  Subclasses :class:`MemoryError` so the
    crash flight-recorder's OOM-forensics path (``telemetry_memory``'s
    ``forensics()`` section in :meth:`FlightRecorder.dump`) exercises
    under chaos exactly as it would under a real allocator failure,
    while tests can still assert the chaos-layer origin."""


class StreamCorruption(FaultInjectionError):
    """A token stream failed an integrity check mid-tick (the
    truncated/garbled-stream fault).  Raised from ``step()`` — the
    gateway's step-exception isolation quarantines the replica and
    replays its in-flight work after the documented
    ``on_token(gid, None, False)`` replay signal, so the partial prefix
    is discarded, never double-delivered."""


# ------------------------------------------------------------------------
# filesystem fault primitives (checkpoint chaos)
# ------------------------------------------------------------------------

def torn_write(path: str, rng: random.Random) -> int:
    """Truncate ``path`` at a seeded offset — the on-disk shape a crash
    mid-``write()`` leaves (a *torn* file: valid prefix, missing tail).
    The offset is drawn from ``rng`` in ``[1, size)`` so at least one
    byte survives and at least one byte is lost; returns the new size.
    Empty/1-byte files are truncated to 0."""
    size = os.path.getsize(path)
    keep = rng.randrange(1, size) if size > 1 else 0
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def corrupt_file(path: str, rng: random.Random, n_bytes: int = 4) -> int:
    """Flip ``n_bytes`` seeded byte positions in ``path`` (XOR with a
    seeded nonzero mask) — post-commit bitrot: the file exists, its size
    is right, its *content* is wrong, so only a content digest catches
    it.  Returns the number of bytes actually flipped."""
    size = os.path.getsize(path)
    if size == 0:
        return 0
    flipped = 0
    with open(path, "r+b") as f:
        for _ in range(max(1, int(n_bytes))):
            off = rng.randrange(size)
            f.seek(off)
            old = f.read(1)
            f.seek(off)
            f.write(bytes([old[0] ^ rng.randrange(1, 256)]))
            flipped += 1
    return flipped


class Fault:
    """One typed fault.  ``kind`` is one of :data:`FAULT_KINDS`; ``at_s``
    is the (injected-clock) second it arms; ``duration_s`` bounds the
    window for windowed kinds (``stall``/``slow``/``dispatch_error``/
    ``garble``; crash is forever by definition).  Kind-specific knobs:

    - ``slow``: ``factor`` — the latency multiplier (10 = a 10× slower
      straggler: one real scheduler round per ``factor`` driver ticks);
    - ``dispatch_error``: ``count`` — at most this many injected
      failures inside the window (None = every dispatch in the window);
    - ``warmup_fail``: ``count`` — the first N ``warmup()`` calls raise
      (time-independent: warmup happens before traffic);
    - ``garble``: ``count`` — at most N corruption events (each one
      raises :class:`StreamCorruption` after the tick's partial
      delivery);
    - ``alloc_fail``: ``count`` — at most N injected allocation
      failures (each ``step()`` in the window raises
      :class:`InjectedAllocationError` before the inner engine runs —
      the OOM shape the flight recorder's forensics dump is tested
      against);
    - ``torn_write`` / ``corrupt_file``: filesystem faults — never fired
      by :class:`FaultyEngine`; ``CheckpointManager`` consults them at
      save time with its save-ordinal clock (``at_s`` = save index) and
      applies :func:`torn_write` (truncate mid-save → the step stays
      uncommitted) or :func:`corrupt_file` (flip bytes *after* commit →
      only the digest verification in ``latest()`` catches it);
      ``count`` bounds how many saves are hit.

    ``replica=None`` matches every replica; a name targets one (the
    :meth:`FaultPlan.for_replica` selector)."""

    __slots__ = ("kind", "at_s", "duration_s", "factor", "count",
                 "replica")

    def __init__(self, kind: str, at_s: float = 0.0,
                 duration_s: Optional[float] = None, factor: float = 10.0,
                 count: Optional[int] = None,
                 replica: Optional[str] = None):
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; choose from "
                             f"{FAULT_KINDS}")
        if float(at_s) < 0:
            raise ValueError("at_s must be >= 0")
        if duration_s is not None and float(duration_s) <= 0:
            raise ValueError("duration_s must be > 0")
        if float(factor) < 1.0:
            raise ValueError("slow factor must be >= 1")
        if count is not None and int(count) < 1:
            raise ValueError("count must be >= 1")
        self.kind = kind
        self.at_s = float(at_s)
        self.duration_s = None if duration_s is None else float(duration_s)
        self.factor = float(factor)
        self.count = None if count is None else int(count)
        self.replica = replica

    def active(self, now: float) -> bool:
        """Inside the fault's window at injected-clock ``now``?  A crash
        never ends; other kinds without ``duration_s`` are open-ended
        too (the plan author said "from t onward")."""
        if now < self.at_s:
            return False
        if self.duration_s is None:
            return True
        return now < self.at_s + self.duration_s

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Fault":
        return cls(**{k: d[k] for k in cls.__slots__ if k in d})

    def __repr__(self):
        win = ("" if self.duration_s is None
               else f"+{self.duration_s:g}s")
        tgt = "" if self.replica is None else f" @{self.replica}"
        return f"Fault({self.kind}, t={self.at_s:g}{win}{tgt})"


class FaultPlan:
    """An ordered, seeded chaos scenario: the faults plus the seed any
    probabilistic consumer must draw from (:class:`FaultyEngine` derives
    a per-replica ``random.Random`` from it), so one plan value replays
    one trajectory.  JSON round-trips via :meth:`to_dict` /
    :meth:`from_dict` / :meth:`from_json` — the shape ``bench.py
    gpt_chaos`` records and ``tools/serve_gateway.py --chaos`` parses."""

    def __init__(self, faults: Sequence[Fault] = (), seed: int = 0):
        self.faults: List[Fault] = sorted(faults, key=lambda f: f.at_s)
        self.seed = int(seed)

    def add(self, fault: Fault) -> "FaultPlan":
        self.faults.append(fault)
        self.faults.sort(key=lambda f: f.at_s)
        return self

    def for_replica(self, name: Optional[str]) -> List[Fault]:
        """The faults that target ``name`` (untargeted faults match
        every replica)."""
        return [f for f in self.faults
                if f.replica is None or f.replica == name]

    def rng(self, name: Optional[str] = None) -> random.Random:
        """A deterministic per-replica RNG: same plan seed + same
        replica name → same draw sequence, independent of every other
        replica's."""
        return random.Random(f"{self.seed}:{name}")

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "faults": [f.to_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultPlan":
        return cls([Fault.from_dict(f) for f in d.get("faults", ())],
                   seed=d.get("seed", 0))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """Parse a plan from JSON: either the ``to_dict`` shape or a
        bare list of fault dicts."""
        data = json.loads(text)
        if isinstance(data, list):
            data = {"faults": data}
        return cls.from_dict(data)

    def __len__(self):
        return len(self.faults)

    def __repr__(self):
        return f"FaultPlan({self.faults!r}, seed={self.seed})"


class FaultyEngine:
    """Wrap any serving engine and inject a :class:`FaultPlan` into its
    scheduling surface (module docstring).  ``replica`` names this
    wrapper for fault targeting; ``clock`` is the injected timebase the
    fault windows read.  Everything not intercepted delegates to the
    inner engine (``tracer``, ``_free_slots``, metrics, prefix caches —
    the gateway sees the wrapper as the engine).

    Injection points:

    - ``step()``: a **crash**/**stall** window freezes the engine — the
      inner ``step`` is not called, so no tokens move and no tracer
      events appear (the gateway's stall health-check sees a silent
      replica and quarantines it, exactly like a wedged device).  A
      **slow** window forwards only every ``factor``-th call (the
      straggler shape hedging exists for).  A **garble** event forwards
      the tick — delivering that tick's partial token prefix — then
      raises :class:`StreamCorruption` (the gateway's step isolation
      quarantines + replays).
    - ``add_request()``: inside a **dispatch_error** window (while its
      ``count`` lasts) raises :class:`TransientDispatchError` BEFORE
      touching the inner engine — the retryable shape.
    - ``warmup()``: while **warmup_fail** has count left, raises.

    ``injected()`` reports what actually fired, for report honesty."""

    def __init__(self, engine, plan: FaultPlan,
                 clock: Callable[[], float], replica: Optional[str] = None,
                 logger: Optional[logging.Logger] = None):
        # object.__setattr__ not needed: __getattr__ only fires on misses
        self.engine = engine
        self.plan = plan
        self.replica = replica
        self._clock = clock
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._faults = plan.for_replica(replica)
        self._rng = plan.rng(replica)
        self._slow_phase = 0
        self._spent: Dict[int, int] = {}     # id(fault) -> injections used
        self._injected: List[Dict[str, Any]] = []
        self.dead = False

    # ------------------------------------------------------------ helpers --

    def _active(self, kind: str, now: float) -> Optional[Fault]:
        for f in self._faults:
            if f.kind == kind and f.active(now):
                return f
        return None

    def _consume(self, fault: Fault) -> bool:
        """Use one injection from a counted fault; False when its count
        is exhausted (the fault stops firing)."""
        if fault.count is None:
            return True
        used = self._spent.get(id(fault), 0)
        if used >= fault.count:
            return False
        self._spent[id(fault)] = used + 1
        return True

    def _note(self, kind: str, **fields):
        self._injected.append({"kind": kind, "t": self._clock(), **fields})

    def injected(self) -> List[Dict[str, Any]]:
        """Every fault actually fired, in firing order — the ground
        truth a chaos report checks its scenario against."""
        return list(self._injected)

    # -------------------------------------------------- injected surface --

    def add_request(self, prompt, max_new_tokens: int, on_token=None,
                    **kwargs) -> int:
        now = self._clock()
        fault = self._active("dispatch_error", now)
        if fault is not None and self._consume(fault):
            self._note("dispatch_error")
            raise TransientDispatchError(
                f"injected dispatch failure (t={now:g})")
        return self.engine.add_request(prompt, max_new_tokens,
                                       on_token=on_token, **kwargs)

    def step(self):
        now = self._clock()
        if self.dead or self._active("crash", now) is not None:
            if not self.dead:
                self.dead = True          # a crash is forever
                self._note("crash")
            return
        if self._active("stall", now) is not None:
            if not self._injected or self._injected[-1]["kind"] != "stall":
                self._note("stall")
            return
        slow = self._active("slow", now)
        if slow is not None:
            self._slow_phase += 1
            if self._slow_phase % max(int(slow.factor), 1) != 0:
                # straggling: skip the real round, but show LIVENESS —
                # a straggler's scheduler loop is running (its tracer
                # heartbeats), it just delivers slowly; without this the
                # stall health-check would collapse slow into crash
                tr = getattr(self.engine, "tracer", None)
                if tr is not None and hasattr(tr, "tick"):
                    tr.tick(type(self.engine).__name__, 0.0, slow=True)
                return
        alloc = self._active("alloc_fail", now)
        if alloc is not None and self._consume(alloc):
            self._note("alloc_fail")
            raise InjectedAllocationError(
                f"injected allocation failure (t={now:g})")
        garble = self._active("garble", now)
        fire_garble = (garble is not None and self._pending_inner()
                       and self._consume(garble))
        out = self.engine.step()
        if fire_garble:
            self._note("garble")
            raise StreamCorruption(
                f"injected token-stream corruption (t={now:g})")
        return out

    def _pending_inner(self) -> bool:
        try:
            return bool(self.engine.pending())
        except Exception:  # noqa: BLE001 — a broken inner engine must not
            # mask the fault we were about to inject
            return True

    def warmup(self, *args, **kwargs):
        fault = self._active("warmup_fail", self._clock())
        if fault is not None and self._consume(fault):
            self._note("warmup_fail")
            raise FaultInjectionError("injected warmup failure")
        return self.engine.warmup(*args, **kwargs)

    def kill(self):
        """Imperative crash (the PR 11 ``SimEngine.kill`` shape) — for
        ``sim.at(t, engine.kill)``-style injections outside a plan."""
        self.dead = True
        self._note("crash", imperative=True)

    # ------------------------------------------------- transparent rest --

    def cancel(self, rid: int) -> bool:
        return self.engine.cancel(rid)

    def pending(self) -> bool:
        return self.engine.pending()

    def pop_finished(self) -> Dict[int, List[int]]:
        return self.engine.pop_finished()

    def __getattr__(self, name):
        # everything else — tracer, _free_slots, _queue, compile_grid,
        # metrics, prefix-cache internals — is the inner engine's
        return getattr(self.engine, name)

    def __repr__(self):
        return (f"FaultyEngine({type(self.engine).__name__}, "
                f"{len(self._faults)} fault(s), replica={self.replica!r})")
