"""``paddle_tpu.jit`` — tracing, export and the dy2static replacement.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ (@to_static AST
transpiler), jit.save/load (TranslatedLayer).  Here: @to_static = jax.jit
over the functionalized layer; jit.save exports a StableHLO artifact via
``jax.export`` (the serialized-program analog of ``__model__`` ProgramDesc).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from .functional import (functionalize, make_eval_step, make_train_step,  # noqa: F401
                         sync_state_to_layer, unwrap_tree, warm_train_step,
                         wrap_tree)
from .bucketing import (bucketize, length_mask, pad_to_bucket,  # noqa: F401
                        pow2_bucket, pow2_grid)
from .aot import (ExecutableCache, compile_aot,  # noqa: F401
                  enable_persistent_compilation_cache, fingerprint,
                  run_warmup, warmup_async)


class InputSpec:
    """Reference: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def to_shape_dtype(self, batch_size=1):
        shape = [batch_size if (s is None or s == -1) else s for s in self.shape]
        return jax.ShapeDtypeStruct(tuple(shape), self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class StaticFunction:
    """A layer/function wrapped for traced execution (≙ program_translator.py
    StaticFunction)."""

    def __init__(self, fn_or_layer, input_spec: Optional[Sequence[InputSpec]] = None):
        from ..nn import Layer
        self._input_spec = list(input_spec) if input_spec else None
        self._orig = fn_or_layer
        if isinstance(fn_or_layer, Layer):
            self._layer = fn_or_layer
            self._apply_fn, _, _ = functionalize(fn_or_layer)

            def run(*args, **kwargs):
                params, buffers = self._layer.raw_state()
                out, _ = self._jitted(params, buffers, *unwrap_tree(list(args)),
                                      **unwrap_tree(kwargs))
                return wrap_tree(out)

            self._jitted = jax.jit(
                lambda p, b, *a, **k: self._apply_fn(p, b, *a, training=False, **k))
            self._call = run
        else:
            self._layer = None
            fn = fn_or_layer

            def pure(*args, **kwargs):
                return unwrap_tree(fn(*wrap_tree(list(args)), **wrap_tree(kwargs)))

            self._jitted = jax.jit(pure)
            self._call = lambda *a, **k: wrap_tree(self._jitted(*unwrap_tree(list(a)),
                                                                **unwrap_tree(k)))

    def __call__(self, *args, **kwargs):
        if not _to_static_state["enabled"]:
            return self._orig(*args, **kwargs)  # ProgramTranslator.enable(False)
        from ..core.tensor import note_compiled_call
        note_compiled_call()  # compiled calls (cache hits too) reset the nudge
        return self._call(*args, **kwargs)

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """``@paddle.jit.to_static`` parity."""
    def decorate(fn):
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs) -> None:
    """``paddle.jit.save`` — serialize a StableHLO program + weights.

    Artifact layout (≙ __model__ + params of save_inference_model io.cc):
      path + ".pdmodel"  — serialized StableHLO (jax.export bytes)
      path + ".pdiparams" — pickled weights/buffers
      path + ".pdmeta"   — input specs & structure info
    """
    from ..nn import Layer
    from jax import export as jax_export

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    apply_fn, params, buffers = functionalize(layer)
    if input_spec is None:
        spec = getattr(layer, "_input_spec", None)
        if spec is None:
            raise ValueError("input_spec is required (layer has no recorded spec)")
        input_spec = spec
    shapes = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            shapes.append(s.to_shape_dtype())
        elif isinstance(s, Tensor):
            shapes.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
        else:
            shapes.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))

    def infer(p, b, *args):
        out, _ = apply_fn(p, b, *args, training=False)
        return out

    jitted = jax.jit(infer)
    exported = jax_export.export(jitted)(
        jax.tree_util.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
        jax.tree_util.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), buffers),
        *shapes)
    blob = exported.serialize()
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": {k: np.asarray(v) for k, v in params.items()},
                     "buffers": {k: np.asarray(v) for k, v in buffers.items()}}, f,
                    protocol=4)
    names = [getattr(s, "name", None) or f"x{i}"
             for i, s in enumerate(input_spec)]
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump({"n_inputs": len(shapes),
                     "input_names": names,
                     "input_shapes": [tuple(s.shape) for s in shapes],
                     "input_dtypes": [str(np.dtype(s.dtype)) for s in shapes]}, f)


class TranslatedLayer:
    """Loaded inference program (≙ dygraph TranslatedLayer)."""

    def __init__(self, exported, params, buffers):
        self._exported = exported
        self._params = {k: jnp.asarray(v) for k, v in params.items()}
        self._buffers = {k: jnp.asarray(v) for k, v in buffers.items()}

    def __call__(self, *args):
        out = self._exported.call(self._params, self._buffers,
                                  *unwrap_tree(list(args)))
        return wrap_tree(out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")


def load(path: str, **configs) -> TranslatedLayer:
    """``paddle.jit.load`` parity."""
    from jax import export as jax_export
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        weights = pickle.load(f)
    layer = TranslatedLayer(exported, weights["params"], weights["buffers"])
    meta_path = path + ".pdmeta"
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            layer._meta = pickle.load(f)
    else:
        layer._meta = {}
    return layer


def not_to_static(fn):
    return fn


def ignore_module(modules):
    pass


_to_static_state = {"enabled": True}


def enable_to_static(flag: bool):
    """Globally toggle to_static wrappers: when off, wrapped callables run
    their original eager code (reference ProgramTranslator.enable)."""
    _to_static_state["enabled"] = bool(flag)


# -- legacy dy2static surface (reference jit/__init__.py re-exports) --------

declarative = to_static  # pre-2.0 name for @to_static

_verbosity = {"level": 0}


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static transpile logging knob (reference logging_utils.py:182).
    This build traces instead of AST-transpiling, so the knob only gates the
    (rare) trace diagnostics."""
    _verbosity["level"] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """Reference logging_utils.py:221 — shows transformed code; tracing has
    no transformed source, so this records the knob for API parity."""
    _verbosity["code_level"] = int(level)


class ProgramTranslator:
    """Singleton switch for dy2static (reference program_translator.py).
    ``enable(False)`` makes to_static-wrapped callables run eagerly."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @property
    def enable_to_static(self):
        return _to_static_state["enabled"]

    def enable(self, flag: bool):
        enable_to_static(bool(flag))


class TracedLayer:
    """Legacy trace-and-serve wrapper (reference dygraph/jit.py TracedLayer).
    ``trace`` jits the layer on example inputs; ``save_inference_model``
    writes the same StableHLO artifact as jit.save."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._static = StaticFunction(layer)
        self._example = inputs

    @staticmethod
    def trace(layer, inputs):
        out = layer(*inputs)
        return out, TracedLayer(layer, inputs)

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        save(self._layer, path, input_spec=list(self._example))


def dy2static_unsupported(*a, **k):
    raise RuntimeError("AST transpilation is replaced by tracing in this "
                       "framework; decorate with @paddle.jit.to_static")
