"""Shape bucketing for variable-length inputs.

The reference handles ragged/variable-length batches with LoD tensors and
dynamic shapes (python/paddle/fluid/lod_tensor.py; declared a non-goal in
SURVEY §7 because XLA requires static shapes).  The TPU-native answer is
*bucketing*: pad every dynamic axis up to the smallest admissible bucket so
a workload with arbitrary lengths compiles at most ``len(buckets)`` XLA
programs — the standard serving/training recipe on TPU.

    step = paddle.jit.bucketize(fn, buckets=(128, 256, 512), axis=1,
                                length_arg="length")
    out = step(ids)              # ids (B, 137) -> padded to (B, 256), one
                                 # compile per bucket ever

``fn`` receives padded arrays (and, when ``length_arg`` is set, the true
length as a traced int32 scalar so it can mask — lengths vary per call
WITHOUT recompiling).  Outputs whose ``axis`` dim equals the bucket are
sliced back to the true length.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp


def select_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket admitting ``length`` — the single bucket policy
    shared by bucketize() and the serving engine, so the selection rule
    (and its error contract) cannot drift between them."""
    bucket = next((b for b in buckets if b >= length), None)
    if bucket is None:
        raise ValueError(
            f"length {length} exceeds the largest bucket {max(buckets)}; "
            f"add a bucket or truncate the input")
    return bucket


def pow2_bucket(need: int, cap: int) -> int:
    """Smallest power of two covering ``need``, clamped to ``cap`` — the
    table-width bucket policy shared by the paged/ragged serving engines'
    view selection AND their warmup grids, so the set of programs warmup
    precompiles is by construction the set serving can dispatch."""
    C = 1
    while C < max(int(need), 1):
        C *= 2
    return min(C, int(cap))


def pow2_grid(cap: int):
    """Every value :func:`pow2_bucket` can return for a given ``cap``:
    powers of two below it plus the clamp value itself — the full
    table-width compile grid a paged/ragged engine enumerates for warmup
    (at most ``log2(cap) + 1`` entries)."""
    cap = int(cap)
    if cap < 1:
        raise ValueError("cap must be >= 1")
    out = []
    C = 1
    while C < cap:
        out.append(C)
        C *= 2
    out.append(cap)
    return tuple(out)


def pad_to_bucket(x, bucket: int, axis: int, pad_value=0):
    """Pad ``x`` along ``axis`` up to ``bucket`` with ``pad_value``."""
    cur = x.shape[axis]
    if cur == bucket:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, bucket - cur)
    return jnp.pad(x, pads, constant_values=pad_value)


def bucketize(fn: Callable, buckets: Sequence[int], axis: int = 1,
              pad_value=0, length_arg: Optional[str] = None,
              unpad_outputs: bool = True, tracer=None) -> Callable:
    """Wrap ``fn`` so calls with any length ≤ max(buckets) reuse a bounded
    set of compiled programs.  Array positional args whose ``axis`` size
    matches the leading arg's are padded together; scalars/mismatched args
    pass through untouched.

    Compile visibility: the wrapper exposes ``bucket_calls`` ({bucket:
    call count}); a bucket's FIRST call — the one that pays the XLA
    compile — bumps the global ``bucketize_bucket_compiles`` stat and,
    with a ``paddle_tpu.telemetry.Tracer`` passed as ``tracer``, emits a
    wall-timed compile event (later calls emit compile hits), so bucket
    churn shows up in the same place the serving engines report recompile
    storms.
    """
    bkts = sorted(set(int(b) for b in buckets))
    if not bkts:
        raise ValueError("buckets must be non-empty")
    jfn = jax.jit(fn)
    calls = {}
    name = getattr(fn, "__name__", "bucketized")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        arrs = [a for a in args if hasattr(a, "shape") and a.ndim > axis]
        if not arrs:
            raise ValueError(f"no array argument with ndim > {axis}")
        L = arrs[0].shape[axis]
        bucket = select_bucket(L, bkts)
        first = bucket not in calls
        calls[bucket] = calls.get(bucket, 0) + 1
        padded = tuple(
            pad_to_bucket(a, bucket, axis, pad_value)
            if hasattr(a, "shape") and a.ndim > axis and a.shape[axis] == L
            else a
            for a in args)
        if length_arg is not None:
            kwargs = dict(kwargs)
            kwargs[length_arg] = jnp.asarray(L, jnp.int32)
        if first:
            from ..utils.stats import stat_add
            stat_add("bucketize_bucket_compiles")
            t0 = time.perf_counter()
            out = jfn(*padded, **kwargs)
            if tracer is not None:
                jax.block_until_ready(out)
                tracer.compile_event(name, (f"bucketize:{name}", bucket),
                                     False, time.perf_counter() - t0)
        else:
            out = jfn(*padded, **kwargs)
            if tracer is not None:
                tracer.compile_event(name, (f"bucketize:{name}", bucket),
                                     True)

        if not unpad_outputs:
            return out

        def unpad(o):
            if hasattr(o, "shape") and o.ndim > axis and o.shape[axis] == bucket:
                return jax.lax.slice_in_dim(o, 0, L, axis=axis)
            return o

        return jax.tree_util.tree_map(unpad, out)

    def warmup(*args, **kwargs):
        """Precompile EVERY bucket from one example call: each matching
        array arg is padded/sliced along ``axis`` to each bucket width and
        dispatched once (outputs discarded, compile accounting identical
        to a real first call) — the grid-enumeration hook the AOT warmup
        planner drives so no live request ever pays a bucket's first
        compile.  Returns the list of buckets warmed this call."""
        arrs = [a for a in args if hasattr(a, "shape") and a.ndim > axis]
        if not arrs:
            raise ValueError(f"no array argument with ndim > {axis}")
        L = arrs[0].shape[axis]
        warmed = []
        for b in bkts:
            def resize(a):
                if not (hasattr(a, "shape") and a.ndim > axis
                        and a.shape[axis] == L):
                    return a
                if a.shape[axis] > b:
                    return jax.lax.slice_in_dim(a, 0, b, axis=axis)
                return pad_to_bucket(a, b, axis, pad_value)
            out = wrapper(*tuple(resize(a) for a in args), **kwargs)
            # tpulint: disable=blocking-fetch-in-loop(warmup loop — each bucket's compile must COMPLETE before the next is declared warm)
            jax.block_until_ready(out)
            warmed.append(b)
        return warmed

    wrapper.buckets = tuple(bkts)
    wrapper.bucket_calls = calls
    wrapper.warmup = warmup
    return wrapper


def length_mask(length, bucket: int, dtype=jnp.float32):
    """(bucket,) mask: 1 for positions < length, 0 for padding — the masking
    companion for ``length_arg`` consumers (e.g. mean-pool over real tokens
    only)."""
    return (jnp.arange(bucket) < length).astype(dtype)
