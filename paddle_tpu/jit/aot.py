"""AOT compilation: persistent executable cache + warmup planner.

The reference keeps compiled programs ACROSS requests and restarts — the
Executor program cache (L6) and ``analysis_predictor``'s serialized inference
programs (L7) mean a serving process never pays a compile on the request
path.  This module is the TPU-native equivalent for a framework whose
"program" is an XLA executable:

1. **Persistent executable cache** (:class:`ExecutableCache`): compiled
   programs keyed by (program digest, input avals/shardings, mesh,
   jax + jaxlib version, backend) and serialized to a cache directory via
   ``jax.experimental.serialize_executable``.  A second process pointing at
   the same directory deserializes instead of recompiling.  Entries whose
   recorded environment no longer matches (jax upgraded, different backend,
   different mesh) are refused at load time — never silently executed.

2. **XLA compilation-cache fallback** (:func:`enable_persistent_compilation_
   cache`): programs that cannot be explicitly serialized (or that dispatch
   through ``jax.jit``'s own call path, like the serving engines' programs)
   still persist across processes through ``jax.config``'s compilation-cache
   settings — the second process re-traces (cheap) and skips the XLA compile
   (the expensive part).  The in-process jit cache is the second level on
   top.

3. **Warmup planner** (:func:`run_warmup` / :func:`warmup_async`): engines
   and step builders declare their compile grid (``engine.compile_grid()``
   enumerates the bucket/table-width program families behind
   ``serving_paged.py`` — the ragged engine's grid is one program per
   (token_budget, table-width) bucket whether or not a draft model is
   attached: speculation swaps the family, it never widens the grid;
   training steps AOT-compile via
   :func:`compile_aot`), and the planner precompiles it — optionally on a
   background thread — before traffic.  Progress reports through the
   telemetry tracer: compile events gain a ``provenance`` tag
   (``cold`` = fresh XLA compile, ``disk`` = served from the persistent
   cache, ``warm`` = already in process) and warmup-window misses never arm
   the recompile-storm warning.

See docs/COMPILATION.md for the cache layout and the soundness conditions
for disk reuse.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

__all__ = ["ExecutableCache", "WarmupTask", "compile_aot",
           "enable_persistent_compilation_cache", "fingerprint",
           "mesh_signature", "run_warmup", "serialization_supported",
           "warmup_async"]

SCHEMA_VERSION = 1
_MANIFEST = "manifest.json"
_log = logging.getLogger(__name__)


def _versions() -> Tuple[str, str]:
    import jaxlib
    return jax.__version__, jaxlib.__version__


def backend_name(backend: Optional[str] = None) -> str:
    return backend if backend is not None else jax.default_backend()


def mesh_signature(mesh) -> Optional[str]:
    """Canonical string for a ``jax.sharding.Mesh``: axis layout plus the
    device kinds under it.  Executables bake in device assignment, so a
    cache entry compiled for one mesh must never load on another."""
    if mesh is None:
        return None
    devs = list(mesh.devices.flat)
    kinds = sorted({getattr(d, "device_kind", str(d)) for d in devs})
    axes = tuple((str(k), int(v)) for k, v in dict(mesh.shape).items())
    return f"axes={axes}|kinds={kinds}|n={len(devs)}"


def _rules_digest() -> str:
    """The active sharding-rules digest (distributed/sharding_rules.py).
    Lazy import: jit/ must stay importable without the distributed layer
    (and vice versa — sharding_rules itself never imports jit/)."""
    from ..distributed.sharding_rules import sharding_rules_digest
    return sharding_rules_digest()


def fingerprint(*parts, mesh=None, backend: Optional[str] = None,
                include_env: bool = True) -> str:
    """Stable hex digest over ``parts`` — THE cache-key helper.  By default
    the compile environment (jax + jaxlib version, backend, mesh signature,
    sharding-rules digest) is folded in, so a key computed under one
    toolchain — or one sharding-rule table — can never alias an executable
    built under another.  Parts are ``repr``-canonicalized; pass
    shapes/dtypes, program text, or config tuples — not live arrays."""
    h = hashlib.blake2b(digest_size=16)
    env: Tuple[Any, ...] = ()
    if include_env:
        jaxv, jaxlibv = _versions()
        env = (jaxv, jaxlibv, backend_name(backend), mesh_signature(mesh),
               _rules_digest())
    for p in env + tuple(parts):
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


def serialization_supported() -> bool:
    """Whether the installed jax can serialize compiled executables."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
    except ImportError:
        return False
    return True


class ExecutableCache:
    """Persistent compiled-executable store (module docstring, point 1).

    Layout::

        <cache_dir>/manifest.json      versioned index: digest -> entry
        <cache_dir>/<digest>.bin       pickled (payload, in_tree, out_tree)
                                       from serialize_executable.serialize
        <cache_dir>/xla/               XLA compilation-cache fallback files
                                       (enable_persistent_compilation_cache)

    Every manifest entry records the environment it was compiled under
    (jax, jaxlib, backend, mesh signature); :meth:`get` refuses mismatching
    entries (counted in ``invalidated``) — a stale executable is recompiled,
    never run.  Deserialized executables are memoized in-process (the
    second-level cache), so repeated ``get`` calls cost a dict lookup.
    """

    def __init__(self, cache_dir, backend: Optional[str] = None):
        self.dir = str(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.backend = backend_name(backend)
        self._lock = threading.Lock()
        self._mem: Dict[str, Any] = {}
        self.hits_memory = 0
        self.hits_disk = 0
        self.misses = 0
        self.invalidated = 0

    # ------------------------------------------------------------ manifest --

    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    @contextlib.contextmanager
    def _manifest_write_lock(self):
        """Cross-PROCESS exclusion for the manifest read-modify-write: the
        advertised use is multi-process (tools/warmup.py at image build +
        a serving host warming the same dir), and two concurrent put()s
        under only the instance lock would last-writer-win, orphaning the
        loser's payload as a silent permanent miss.  flock on a sidecar
        lock file; readers need nothing (os.replace keeps the manifest
        itself always-consistent)."""
        with open(os.path.join(self.dir, "manifest.lock"), "w") as f:
            try:
                import fcntl
            except ImportError:           # non-POSIX: in-process lock only
                yield
                return
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)

    def _load_manifest(self) -> Dict[str, Any]:
        try:
            with open(self._manifest_path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return {"version": SCHEMA_VERSION, "entries": {}}
        except (OSError, ValueError) as e:
            _log.warning("aot cache manifest %s unreadable (%s) — treating "
                         "as empty", self._manifest_path, e)
            return {"version": SCHEMA_VERSION, "entries": {}}
        if data.get("version") != SCHEMA_VERSION:
            _log.warning("aot cache manifest version %r != %d — ignoring "
                         "existing entries", data.get("version"),
                         SCHEMA_VERSION)
            return {"version": SCHEMA_VERSION, "entries": {}}
        return data

    def _write_atomic(self, path: str, blob: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def _digest(self, key) -> str:
        # env rides the digest too, but the manifest entry is the AUTHORITY:
        # invalidation must be observable (and warn), not a silent miss
        return fingerprint("exec", key, backend=self.backend,
                           include_env=False)

    # ------------------------------------------------------------- put/get --

    def put(self, key, compiled, mesh=None) -> bool:
        """Serialize one compiled executable under ``key``.  Returns False
        (and leaves the cache untouched) when the executable does not
        support serialization — callers fall back to the XLA
        compilation-cache wiring."""
        try:
            from jax.experimental import serialize_executable as se
        except ImportError:
            return False
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
        except (ValueError, TypeError) as e:
            _log.warning("aot cache: %r not serializable (%s); relying on "
                         "the XLA compilation-cache fallback", key, e)
            return False
        digest = self._digest(key)
        blob = pickle.dumps((payload, in_tree, out_tree), protocol=4)
        jaxv, jaxlibv = _versions()
        with self._lock, self._manifest_write_lock():
            fname = digest + ".bin"
            self._write_atomic(os.path.join(self.dir, fname), blob)
            manifest = self._load_manifest()   # re-read UNDER the lock:
            # merges entries another process wrote since our last look
            manifest["entries"][digest] = {
                "key": str(key), "file": fname, "jax": jaxv,
                "jaxlib": jaxlibv, "backend": self.backend,
                "mesh": mesh_signature(mesh), "rules": _rules_digest(),
                "bytes": len(blob), "created_at": time.time()}
            self._write_atomic(self._manifest_path,
                               json.dumps(manifest, indent=2,
                                          sort_keys=True).encode())
            self._mem[digest] = compiled
        # serialized-blob bytes feed the memory ledger's `executables`
        # pool (a host-side proxy for compiled-program size) — one
        # attribute check when no ledger is active
        from ..telemetry_memory import account_bytes
        account_bytes("executables", len(blob), space="host")
        return True

    def get(self, key, mesh=None):
        """The executable cached under ``key``, or None on a miss OR an
        environment mismatch (jax/jaxlib/backend/mesh/sharding-rules drift
        invalidates the entry — a recompile is cheaper than a wrong
        program; a stale-spec executable restored from disk must be
        impossible)."""
        digest = self._digest(key)
        with self._lock:
            if digest in self._mem:
                self.hits_memory += 1
                return self._mem[digest]
            entry = self._load_manifest()["entries"].get(digest)
        if entry is None:
            self.misses += 1
            return None
        jaxv, jaxlibv = _versions()
        want = {"jax": jaxv, "jaxlib": jaxlibv, "backend": self.backend,
                "mesh": mesh_signature(mesh), "rules": _rules_digest()}
        for field, expect in want.items():
            if entry.get(field) != expect:
                self.invalidated += 1
                _log.warning(
                    "aot cache entry %r invalidated: %s was %r, now %r — "
                    "recompiling", entry.get("key"), field,
                    entry.get(field), expect)
                return None
        try:
            with open(os.path.join(self.dir, entry["file"]), "rb") as f:
                blob = f.read()
        except OSError as e:
            self.misses += 1
            _log.warning("aot cache entry %r lost its payload (%s)",
                         entry.get("key"), e)
            return None
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — a corrupt/incompatible
            # payload must degrade to a recompile, never kill serving
            self.invalidated += 1
            _log.warning("aot cache entry %r failed to deserialize (%s) — "
                         "recompiling", entry.get("key"), e)
            return None
        with self._lock:
            self._mem[digest] = compiled
            self.hits_disk += 1
        # a disk restore brings the blob into process memory too
        from ..telemetry_memory import account_bytes
        account_bytes("executables", len(blob), space="host")
        return compiled

    def contains(self, key) -> bool:
        digest = self._digest(key)
        with self._lock:
            if digest in self._mem:
                return True
            return digest in self._load_manifest()["entries"]

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._load_manifest()["entries"].values())

    def stats(self) -> Dict[str, int]:
        return {"hits_memory": self.hits_memory, "hits_disk": self.hits_disk,
                "misses": self.misses, "invalidated": self.invalidated}


# ---------------------------------------------------------------------------
# XLA compilation-cache fallback wiring
# ---------------------------------------------------------------------------

def enable_persistent_compilation_cache(cache_dir) -> str:
    """Point jax's XLA persistent compilation cache at ``<cache_dir>/xla``
    (created if needed) and drop the min-compile-time / min-entry-size
    gates so EVERY program persists — serving programs are many and small,
    and the whole point is that none of them compiles twice.  Idempotent;
    returns the XLA cache directory."""
    xla_dir = os.path.join(str(cache_dir), "xla")
    os.makedirs(xla_dir, exist_ok=True)
    changed = False
    if jax.config.jax_compilation_cache_dir != xla_dir:
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        changed = True
    if jax.config.jax_persistent_cache_min_compile_time_secs != 0.0:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        changed = True
    if jax.config.jax_persistent_cache_min_entry_size_bytes != -1:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        changed = True
    if changed:
        # jax latches cache-enablement at the FIRST compile of the process
        # (is_cache_used memoizes per task); wiring the dir after any
        # compile has happened — the normal case for an engine warming
        # post-construction — needs the latch reset or nothing persists
        try:
            from jax._src.compilation_cache import reset_cache
        except ImportError:
            _log.warning("jax %s has no compilation_cache.reset_cache; "
                         "programs compiled before this call may not "
                         "persist", jax.__version__)
        else:
            reset_cache()
    return xla_dir


def persistent_cache_dir() -> Optional[str]:
    """The currently wired XLA compilation-cache dir (None = not wired)."""
    return jax.config.jax_compilation_cache_dir


class _DirProvenance:
    """Compile-provenance resolver consulted by the Tracer at compile-event
    time: executable files newly written to the XLA cache dir since the
    last check mean that compile PAID XLA ("cold"); none mean it was served
    from disk ("disk").  Exact for sequential warmup (events fire right
    after each program's first dispatch); concurrent compiles can smear
    attribution between simultaneous tasks."""

    def __init__(self, xla_dir: str):
        self.dir = xla_dir
        self._lock = threading.Lock()
        self._seen = set(os.listdir(xla_dir))

    def __call__(self) -> str:
        with self._lock:
            try:
                now = set(os.listdir(self.dir))
            except OSError:
                return "cold"
            new = now - self._seen
            self._seen = now
        # "-cache" files hold executables; "-atime" stamps ride along on
        # reads too, so only a new executable counts as a cold compile
        return "cold" if any(f.endswith("-cache") for f in new) else "disk"


# ---------------------------------------------------------------------------
# warmup planner
# ---------------------------------------------------------------------------

class WarmupTask:
    """One program family to precompile: ``run()`` must fetch AND dispatch
    the program once (scratch operands), so the XLA compile — not just the
    Python closure build — happens during warmup."""

    __slots__ = ("label", "run")

    def __init__(self, label: str, run: Callable[[], None]):
        self.label = str(label)
        self.run = run

    def __repr__(self):
        return f"WarmupTask({self.label!r})"


def run_warmup(tasks: Sequence[WarmupTask], *, tracer=None, cache_dir=None,
               max_workers: int = 1,
               logger: Optional[logging.Logger] = None) -> Dict[str, Any]:
    """Execute a warmup plan.  ``cache_dir`` wires the persistent XLA
    compilation cache first, so the compiles both PERSIST for later
    processes and RESOLVE provenance (cold vs disk) for this one.  With a
    ``tracer`` the whole run executes inside its ``expected_compiles``
    window: compile events are tagged and the recompile-storm warning
    ignores them.  ``max_workers > 1`` compiles concurrently (provenance
    attribution may smear across simultaneous tasks).  Returns a report:
    ``{"programs", "wall_s", "tasks": [{"label", "wall_s"}, ...],
    "cache_dir"}``."""
    log = logger if logger is not None else _log
    resolver = None
    if cache_dir is not None:
        resolver = _DirProvenance(
            enable_persistent_compilation_cache(cache_dir))
    t0 = time.perf_counter()

    def one(task: WarmupTask) -> Dict[str, Any]:
        tt = time.perf_counter()
        task.run()
        return {"label": task.label, "wall_s": time.perf_counter() - tt}

    # scope the expected window to THIS grid's labels: with warmup_async,
    # live traffic compiles concurrently — its misses must still arm the
    # recompile-storm warning
    ctx = (tracer.expected_compiles(resolver,
                                    keys={t.label for t in tasks})
           if tracer is not None else contextlib.nullcontext())
    with ctx:
        if max_workers and int(max_workers) > 1:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=int(max_workers),
                    thread_name_prefix="aot-warmup") as ex:
                results = list(ex.map(one, tasks))
        else:
            results = [one(t) for t in tasks]
    wall = time.perf_counter() - t0
    log.info("aot warmup: %d programs in %.2fs%s", len(results), wall,
             f" (cache: {cache_dir})" if cache_dir else "")
    return {"programs": len(results), "wall_s": wall, "tasks": results,
            "cache_dir": None if cache_dir is None else str(cache_dir)}


def warmup_async(tasks: Sequence[WarmupTask], **kwargs
                 ) -> "concurrent.futures.Future":
    """``run_warmup`` on a background thread — engines warm while the host
    finishes startup; traffic admitted mid-warmup simply compiles what it
    needs (the warmup task then hits).  Returns the Future of the report."""
    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="aot-warmup-driver")
    fut = ex.submit(run_warmup, tasks, **kwargs)
    ex.shutdown(wait=False)
    return fut


# ---------------------------------------------------------------------------
# training-step AOT
# ---------------------------------------------------------------------------

def compile_aot(step, example_args: Sequence[Any], *, cache: Optional[
        ExecutableCache] = None, mesh=None, label: str = "step",
        monitor=None, key_extra: Tuple = ()):
    """AOT-compile a step via ``.lower().compile()`` with persistent-cache
    reuse — the training-side warmup primitive (``make_train_step`` /
    ``make_gpt_train_step`` steps expose ``lower``; plain callables are
    jitted first).  ``example_args`` may be arrays or ShapeDtypeStructs.

    Key: (label, digest of the lowered StableHLO text + jax/jaxlib/backend/
    mesh + ``key_extra``) — the program CONTENT keys the cache, so any
    config change that alters the lowering misses naturally.  Returns
    ``(compiled, provenance)`` with provenance ``"cold" | "disk" | "warm"``;
    with a ``monitor`` (``telemetry.TrainMonitor``) the compile — or the
    disk load — is recorded as a compile event with that provenance, and
    a cold compile additionally carries the executable's XLA
    cost-analysis FLOPs/bytes (free — the program was just compiled;
    the result seeds ``hapi/dynamic_flops``'s digest cache), the
    per-step model-FLOPs source of the training-side MFU summary."""
    lower = getattr(step, "lower", None)
    lowered = (lower(*example_args) if lower is not None
               else jax.jit(step).lower(*example_args))
    # env stays OUT of the key: the manifest entry is the environment
    # authority, so jax/backend/mesh drift hits the OBSERVABLE
    # invalidation path (warning + counter, entry overwritten in place)
    # instead of silently missing and stranding orphaned payloads
    key = (label, fingerprint("aot_step", lowered.as_text(), *key_extra,
                              include_env=False))

    def _cost(compiled_exe):
        try:
            from ..hapi.dynamic_flops import cost_of_compiled
            return cost_of_compiled(compiled_exe, lowered=lowered)
        except Exception:  # noqa: BLE001 — best-effort telemetry only
            return None

    if cache is not None:
        mem_before = cache.hits_memory
        t0 = time.perf_counter()
        cached = cache.get(key, mesh=mesh)
        if cached is not None:
            provenance = "warm" if cache.hits_memory > mem_before else "disk"
            if monitor is not None:
                monitor.record_compile((f"{label}_aot",),
                                       time.perf_counter() - t0,
                                       provenance=provenance,
                                       cost=_cost(cached))
            return cached, provenance
    t0 = time.perf_counter()
    compiled = lowered.compile()
    wall = time.perf_counter() - t0
    if monitor is not None:
        monitor.record_compile((f"{label}_aot",), wall, provenance="cold",
                               cost=_cost(compiled))
    if cache is not None:
        cache.put(key, compiled, mesh=mesh)
    return compiled, "cold"
