"""Functional bridge: eager Layers → pure jit-able functions.

This is the TPU replacement for the reference's dygraph-to-static transpiler
(fluid/dygraph/dygraph_to_static/ — 25 AST transformer files): instead of
rewriting Python AST into ProgramDesc, the SAME ``forward`` runs under
``jax.jit`` tracing with parameters bound from an explicit pytree
(Layer.bind).  Python control flow is evaluated at trace time (equivalent to
the transpiler's constant-folding path); data-dependent control flow uses
lax.cond/scan as in any JAX program.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import rng
from ..core.tensor import Tensor


def _wrap(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x)
    return x


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def wrap_tree(tree):
    return jax.tree_util.tree_map(_wrap, tree)


def unwrap_tree(tree):
    return jax.tree_util.tree_map(_unwrap, tree,
                                  is_leaf=lambda x: isinstance(x, Tensor))


def functionalize(layer) -> Tuple[Callable, Dict[str, Any], Dict[str, Any]]:
    """Extract (apply_fn, params, buffers) from a Layer.

    ``apply_fn(params, buffers, *args, rng_key=None, training=False,
    **kwargs) -> (outputs_raw, new_buffers)`` is pure and traceable.
    """
    params, buffers = layer.raw_state()

    def apply_fn(p, b, *args, rng_key=None, training=False, **kwargs):
        was_training = layer.training
        layer.train() if training else layer.eval()
        try:
            with layer.bind(p, b):
                ctx = rng.rng_scope(rng_key) if rng_key is not None \
                    else contextlib.nullcontext()
                with ctx:
                    out = layer(*wrap_tree(args),
                                **{k: _wrap(v) for k, v in kwargs.items()})
                new_b = layer.read_buffers(b)
            return unwrap_tree(out), new_b
        finally:
            layer.train() if was_training else layer.eval()

    return apply_fn, params, buffers


def make_train_step(layer, loss_fn, optimizer, donate: bool = True):
    """Build a jit-compiled train step closure over (layer, loss, optimizer).

    Returns ``(step, state0)`` where
    ``step(state, key, lr, *batch) -> (state, loss)`` and state is the
    ``TrainState`` dict pytree {params, opt, buffers}.
    The whole update (fwd+bwd+optimizer) compiles to ONE XLA program —
    the analog of the reference's static-graph train program (§3.1) without
    any ProgramDesc.
    """
    apply_fn, params0, buffers0 = functionalize(layer)
    opt_state0 = optimizer.init_state(params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": buffers0}

    def loss_of(p, b, key, inputs, labels):
        out, new_b = apply_fn(p, b, *inputs, rng_key=key, training=True)
        main_out = out[0] if isinstance(out, (list, tuple)) else out
        loss_t = loss_fn(_wrap(main_out), *wrap_tree(labels))
        return _unwrap(loss_t), (new_b, main_out)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, inputs, labels):
        (loss, (new_b, out)), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state["params"], state["buffers"], key, inputs, labels)
        new_params, new_opt = optimizer.update(grads, state["opt"], state["params"],
                                               lr=lr)
        return {"params": new_params, "opt": new_opt, "buffers": new_b}, (loss, out)

    return step, state0


def make_accum_train_step(layer, loss_fn, optimizer, accum_steps: int,
                          donate: bool = True):
    """Gradient-accumulating train step (≙ GradientMergeOptimizer,
    fluid/optimizer.py:6783): grads from ``accum_steps`` consecutive calls
    are summed in the TrainState; the optimizer applies their mean on every
    ``accum_steps``-th call (lax.cond — one compiled program, no Python
    branching).  Same signature as make_train_step."""
    apply_fn, params0, buffers0 = functionalize(layer)
    opt_state0 = optimizer.init_state(params0)
    acc0 = jax.tree.map(jnp.zeros_like, params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": buffers0,
              "acc": acc0, "acc_count": jnp.zeros((), jnp.int32)}

    def loss_of(p, b, key, inputs, labels):
        out, new_b = apply_fn(p, b, *inputs, rng_key=key, training=True)
        main_out = out[0] if isinstance(out, (list, tuple)) else out
        loss_t = loss_fn(_wrap(main_out), *wrap_tree(labels))
        return _unwrap(loss_t), (new_b, main_out)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, inputs, labels):
        (loss, (new_b, out)), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state["params"], state["buffers"], key, inputs, labels)
        acc = jax.tree.map(lambda a, g: a + g, state["acc"], grads)
        cnt = state["acc_count"] + 1

        def apply(_):
            mean = jax.tree.map(lambda a: a / accum_steps, acc)
            p, o = optimizer.update(mean, state["opt"], state["params"], lr=lr)
            return p, o, jax.tree.map(jnp.zeros_like, acc), jnp.zeros((), jnp.int32)

        def hold(_):
            return state["params"], state["opt"], acc, cnt

        params, opt, acc_out, cnt_out = jax.lax.cond(
            cnt >= accum_steps, apply, hold, None)
        new_state = {"params": params, "opt": opt, "buffers": new_b,
                     "acc": acc_out, "acc_count": cnt_out}
        return new_state, (loss, out)

    return step, state0


def make_eval_step(layer, loss_fn=None):
    apply_fn, _, _ = functionalize(layer)

    @jax.jit
    def step(params, buffers, inputs, labels=None):
        out, _ = apply_fn(params, buffers, *inputs, training=False)
        main_out = out[0] if isinstance(out, (list, tuple)) else out
        if loss_fn is None or labels is None:
            return main_out, None
        loss_t = loss_fn(_wrap(main_out), *wrap_tree(labels))
        return main_out, _unwrap(loss_t)

    return step


def sync_state_to_layer(layer, state) -> None:
    """Write a functional TrainState's params/buffers back into the Layer."""
    named_p = dict(layer.named_parameters())
    for name, val in state["params"].items():
        named_p[name]._data = val
    named_b = dict(layer.named_buffers())
    for name, val in state["buffers"].items():
        if name.startswith("__frozen__."):
            named_p[name[len("__frozen__."):]]._data = val
        else:
            named_b[name]._data = val
