"""Functional bridge: eager Layers → pure jit-able functions.

This is the TPU replacement for the reference's dygraph-to-static transpiler
(fluid/dygraph/dygraph_to_static/ — 25 AST transformer files): instead of
rewriting Python AST into ProgramDesc, the SAME ``forward`` runs under
``jax.jit`` tracing with parameters bound from an explicit pytree
(Layer.bind).  Python control flow is evaluated at trace time (equivalent to
the transpiler's constant-folding path); data-dependent control flow uses
lax.cond/scan as in any JAX program.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import rng
from ..core.tensor import Tensor, note_compiled_call


#: the jit API surface every step wrapper must pass through (tests, AOT
#: benches, and the telemetry wrappers all rely on ``lower`` reaching the
#: SAME underlying program so cache keys and lowerings never fork)
JIT_SURFACE_ATTRS = ("lower", "eval_shape", "trace", "clear_cache")


def copy_jit_surface(src, dst):
    """Copy the jit API surface (:data:`JIT_SURFACE_ATTRS`) from ``src``
    onto the wrapper ``dst`` and return ``dst`` — THE one pass-through
    implementation shared by this module's wrappers and
    ``telemetry.instrument_train_step`` (previously two hand-rolled
    copies that could drift)."""
    for attr in JIT_SURFACE_ATTRS:
        if hasattr(src, attr):
            setattr(dst, attr, getattr(src, attr))
    return dst


def _tracks_compiled_calls(fn):
    """Every invocation (cache hits included) resets the eager-nudge streak
    — see core.tensor.note_compiled_call.  The jit API surface (lower /
    eval_shape / trace — used by tests and AOT benches) passes through."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        note_compiled_call()
        return fn(*args, **kwargs)
    return copy_jit_surface(fn, wrapped)


def _wrap(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x)
    return x


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def wrap_tree(tree):
    return jax.tree_util.tree_map(_wrap, tree)


def unwrap_tree(tree):
    return jax.tree_util.tree_map(_unwrap, tree,
                                  is_leaf=lambda x: isinstance(x, Tensor))


def functionalize(layer) -> Tuple[Callable, Dict[str, Any], Dict[str, Any]]:
    """Extract (apply_fn, params, buffers) from a Layer.

    ``apply_fn(params, buffers, *args, rng_key=None, training=False,
    **kwargs) -> (outputs_raw, new_buffers)`` is pure and traceable.
    """
    params, buffers = layer.raw_state()

    def apply_fn(p, b, *args, rng_key=None, training=False, **kwargs):
        was_training = layer.training
        layer.train() if training else layer.eval()
        try:
            with layer.bind(p, b):
                ctx = rng.rng_scope(rng_key) if rng_key is not None \
                    else contextlib.nullcontext()
                with ctx:
                    out = layer(*wrap_tree(args),
                                **{k: _wrap(v) for k, v in kwargs.items()})
                new_b = layer.read_buffers(b)
            return unwrap_tree(out), new_b
        finally:
            layer.train() if was_training else layer.eval()

    return apply_fn, params, buffers


def _make_loss_of(apply_fn, loss_fn, trace_ctx):
    """Shared traced loss body for the step builders (single copy so
    trace-time behavior — AMP casts etc. — cannot diverge between them)."""
    def loss_of(p, b, key, inputs, labels):
        with (trace_ctx() if trace_ctx is not None else contextlib.nullcontext()):
            out, new_b = apply_fn(p, b, *inputs, rng_key=key, training=True)
            main_out = out[0] if isinstance(out, (list, tuple)) else out
            loss_t = loss_fn(_wrap(main_out), *wrap_tree(labels))
        return _unwrap(loss_t), (new_b, main_out)
    return loss_of


def _make_scaler(scaler_cfg):
    if not scaler_cfg:
        return None
    from ..amp import GradScaler
    return GradScaler(
        init_loss_scaling=float(scaler_cfg.get("init_loss_scaling", 2.0 ** 15)),
        incr_ratio=float(scaler_cfg.get("incr_ratio", 2.0)),
        decr_ratio=float(scaler_cfg.get("decr_ratio", 0.5)),
        incr_every_n_steps=int(scaler_cfg.get("incr_every_n_steps", 1000)),
        decr_every_n_nan_or_inf=int(
            scaler_cfg.get("decr_every_n_nan_or_inf", 1)))


def _scaled_grads(loss_of, state, key, inputs, labels, scaler):
    """Grad computation, optionally under dynamic loss scaling.  All scaler
    math lives in GradScaler.functional_update (≙ check_finite_and_unscale +
    update_loss_scaling ops) — one implementation, shared with eager mode."""
    if scaler is None:
        (loss, (new_b, out)), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state["params"], state["buffers"], key, inputs, labels)
        return loss, new_b, out, grads, {}, None

    scale = state["scaler"]["scale"]

    def scaled(p, b, key, inputs, labels):
        loss, aux = loss_of(p, b, key, inputs, labels)
        return loss * scale.astype(loss.dtype), (loss, aux)

    (_, (loss, (new_b, out))), sgrads = jax.value_and_grad(
        scaled, has_aux=True)(state["params"], state["buffers"], key, inputs,
                              labels)
    unscaled, found_inf, scaler_state = scaler.functional_update(
        state["scaler"], sgrads)
    return loss, new_b, out, unscaled, {"scaler": scaler_state}, found_inf


def _maybe_skip_update(optimizer, grads, state, lr, found_inf):
    """Apply the optimizer unless found_inf (reference found_inf contract)."""
    if found_inf is None:
        return optimizer.update(grads, state["opt"], state["params"], lr=lr)

    def apply(_):
        return optimizer.update(grads, state["opt"], state["params"], lr=lr)

    def skip(_):
        return state["params"], state["opt"]

    return jax.lax.cond(found_inf, skip, apply, None)


def make_train_step(layer, loss_fn, optimizer, donate: bool = True,
                    trace_ctx=None, scaler_cfg=None, monitor=None,
                    grad_comm=None):
    """Build a jit-compiled train step closure over (layer, loss, optimizer).

    Returns ``(step, state0)`` where
    ``step(state, key, lr, *batch) -> (state, loss)`` and state is the
    ``TrainState`` dict pytree {params, opt, buffers}.
    The whole update (fwd+bwd+optimizer) compiles to ONE XLA program —
    the analog of the reference's static-graph train program (§3.1) without
    any ProgramDesc.

    ``trace_ctx``: optional context factory entered at TRACE time (jax.jit
    traces lazily at the first call) — e.g. amp.auto_cast.
    ``scaler_cfg``: optional dict of GradScaler knobs enabling in-step
    dynamic loss scaling (fp16 AMP; bf16 does not need one).
    ``monitor``: optional ``telemetry.TrainMonitor``; wraps the step with
    host-side timing OUTSIDE the jit boundary — the compiled program (and
    its cache key) is identical with or without one, and ``monitor=None``
    returns the bare step.
    ``grad_comm``: gradient-communication policy (``"fp32"`` default /
    ``"bf16"`` / ``"int8_ef"`` / a ``distributed.grad_comm
    .GradCommPolicy``).  This builder has no mesh, so the policy applies
    in LOCAL mode — the quantize/EF numerics of the wire composition at
    R=1 (docs/DISTRIBUTED_COMM.md); stateful policies add a
    ``"comm_e"`` residual leaf to the TrainState.
    """
    from ..distributed.grad_comm import (apply_policy_local, comm_info,
                                         resolve_policy)
    policy = resolve_policy(grad_comm)
    apply_fn, params0, buffers0 = functionalize(layer)
    opt_state0 = optimizer.init_state(params0)
    scaler = _make_scaler(scaler_cfg)
    state0 = {"params": params0, "opt": opt_state0, "buffers": buffers0}
    if scaler is not None:
        state0["scaler"] = scaler.init_state()
    if policy.stateful:
        state0["comm_e"] = policy.residual_for(params0)
    loss_of = _make_loss_of(apply_fn, loss_fn, trace_ctx)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, inputs, labels):
        loss, new_b, out, grads, scaler_state, found_inf = _scaled_grads(
            loss_of, state, key, inputs, labels, scaler)
        grads, comm_state = apply_policy_local(policy, grads, state,
                                               found_inf=found_inf)
        new_params, new_opt = _maybe_skip_update(optimizer, grads, state, lr,
                                                 found_inf)
        return {"params": new_params, "opt": new_opt, "buffers": new_b,
                **scaler_state, **comm_state}, (loss, out)

    from ..telemetry import instrument_train_step
    from ..telemetry_memory import current_memory_ledger
    _ml = current_memory_ledger()
    if _ml is not None:
        # allocation-site registration: the initial state's pools are
        # attributable before the first step (instrument_train_step
        # re-registers the fresh state after each donated rebuild)
        _ml.register_train_state(state0, name="train_step")
    return instrument_train_step(_tracks_compiled_calls(step), monitor,
                                 "train_step",
                                 comm=comm_info(params0, policy)), state0


def make_accum_train_step(layer, loss_fn, optimizer, accum_steps: int,
                          donate: bool = True, trace_ctx=None, monitor=None,
                          grad_comm=None):
    """Gradient-accumulating train step (≙ GradientMergeOptimizer,
    fluid/optimizer.py:6783): grads from ``accum_steps`` consecutive calls
    are summed in the TrainState; the optimizer applies their mean on every
    ``accum_steps``-th call (lax.cond — one compiled program, no Python
    branching).  Same signature as make_train_step.  ``grad_comm`` applies
    at the accumulation boundary — the communication moment — so only the
    every-``accum_steps`` exchange pays (and benefits from) compression."""
    from ..distributed.grad_comm import comm_info, resolve_policy
    policy = resolve_policy(grad_comm)
    apply_fn, params0, buffers0 = functionalize(layer)
    opt_state0 = optimizer.init_state(params0)
    acc0 = jax.tree.map(jnp.zeros_like, params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": buffers0,
              "acc": acc0, "acc_count": jnp.zeros((), jnp.int32)}
    if policy.stateful:
        state0["comm_e"] = policy.residual_for(params0)
    loss_of = _make_loss_of(apply_fn, loss_fn, trace_ctx)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, inputs, labels):
        (loss, (new_b, out)), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state["params"], state["buffers"], key, inputs, labels)
        acc = jax.tree.map(lambda a, g: a + g, state["acc"], grads)
        cnt = state["acc_count"] + 1
        e = state.get("comm_e")

        def apply(_):
            mean = jax.tree.map(lambda a: a / accum_steps, acc)
            mean, new_e = policy.apply_local(mean, e)
            p, o = optimizer.update(mean, state["opt"], state["params"], lr=lr)
            return (p, o, jax.tree.map(jnp.zeros_like, acc),
                    jnp.zeros((), jnp.int32), new_e)

        def hold(_):
            return state["params"], state["opt"], acc, cnt, e

        params, opt, acc_out, cnt_out, e_out = jax.lax.cond(
            cnt >= accum_steps, apply, hold, None)
        new_state = {"params": params, "opt": opt, "buffers": new_b,
                     "acc": acc_out, "acc_count": cnt_out}
        if policy.stateful:
            new_state["comm_e"] = e_out
        return new_state, (loss, out)

    from ..telemetry import instrument_train_step
    from ..telemetry_memory import current_memory_ledger
    _ml = current_memory_ledger()
    if _ml is not None:
        _ml.register_train_state(state0, name="accum_train_step")
    comm = comm_info(params0, policy)
    if comm is not None:
        # the exchange only runs every accum_steps-th call — amortize so
        # per-step comm events stay truthful (ratio unchanged)
        comm = dict(comm, pre_bytes=comm["pre_bytes"] // accum_steps,
                    post_bytes=max(comm["post_bytes"] // accum_steps, 1))
    return instrument_train_step(_tracks_compiled_calls(step), monitor,
                                 "accum_train_step", comm=comm), state0


def warm_train_step(step, example_args, cache=None, monitor=None,
                    label: str = "train_step", mesh=None):
    """AOT-compile a built train step — the ``.lower().compile()`` warmup
    seam for the step builders (make_train_step / make_accum_train_step /
    make_gpt_train_step's GSPMD path all return steps whose ``lower``
    passes through the telemetry wrappers, so the compiled program and
    its cache key are the ones live dispatch would use; the zero_stage>0
    gpt path raises NotImplementedError from ``lower``).

    ``example_args`` are the step's call args (arrays or
    ShapeDtypeStructs); ``cache``: an optional ``jit.aot.ExecutableCache``
    — a second process warming against the same directory loads the
    serialized executable instead of recompiling (``provenance: disk``).
    Returns ``(compiled, provenance)``; call ``compiled(*args)`` in place
    of ``step`` for a zero-compile first step."""
    from .aot import compile_aot
    return compile_aot(step, example_args, cache=cache, monitor=monitor,
                       label=label, mesh=mesh)


def make_eval_step(layer, loss_fn=None):
    apply_fn, _, _ = functionalize(layer)

    @jax.jit
    def step(params, buffers, inputs, labels=None):
        out, _ = apply_fn(params, buffers, *inputs, training=False)
        main_out = out[0] if isinstance(out, (list, tuple)) else out
        if loss_fn is None or labels is None:
            return main_out, None
        loss_t = loss_fn(_wrap(main_out), *wrap_tree(labels))
        return main_out, _unwrap(loss_t)

    return _tracks_compiled_calls(step)


def fold_in_step_key(base_key, step: int):
    """THE per-step RNG derivation: ``key_t = fold_in(base_key, t)``.

    The step key is a pure function of (base key, step index) — no
    mutable split chain — so a training loop resumed at step ``t`` from
    a checkpoint (``train_resilience.CheckpointManager`` stores only the
    base key + the step counter) regenerates bit-identical dropout/noise
    keys for every subsequent step.  Accepts typed (``jax.random.key``)
    and legacy ``uint32`` keys alike."""
    return jax.random.fold_in(base_key, int(step))


def sync_state_to_layer(layer, state) -> None:
    """Write a functional TrainState's params/buffers back into the Layer."""
    named_p = dict(layer.named_parameters())
    for name, val in state["params"].items():
        named_p[name]._data = val
    named_b = dict(layer.named_buffers())
    for name, val in state["buffers"].items():
        if name.startswith("__frozen__."):
            named_p[name[len("__frozen__."):]]._data = val
        else:
            named_b[name]._data = val
