"""``paddle.device.cuda`` API surface (reference: python/paddle/device/cuda).

There is no CUDA in a TPU build; the count/probe entry points answer
truthfully (0 devices) instead of raising, matching the reference's behavior
on a CPU-only build, so device-agnostic user code keeps working.
"""

from __future__ import annotations

__all__ = ["Stream", "Event", "current_stream", "synchronize", "device_count",
           "max_memory_allocated", "max_memory_reserved", "memory_allocated",
           "memory_reserved", "empty_cache"]


def device_count() -> int:
    return 0


def synchronize(device=None):
    from ...core.device import synchronize as _sync
    return _sync()


def current_stream(device=None):
    raise RuntimeError("CUDA streams are unavailable in a TPU/XLA build")


class Stream:
    def __init__(self, *a, **k):
        raise RuntimeError("CUDA streams are unavailable in a TPU/XLA build")


class Event:
    def __init__(self, *a, **k):
        raise RuntimeError("CUDA events are unavailable in a TPU/XLA build")


def max_memory_allocated(device=None) -> int:
    return 0


def max_memory_reserved(device=None) -> int:
    return 0


def memory_allocated(device=None) -> int:
    return 0


def memory_reserved(device=None) -> int:
    return 0


def empty_cache():
    return None
