"""Device management facade (reference: python/paddle/device/__init__.py).

Re-exports the core Place/device machinery (core/device.py) under the
public ``paddle.device`` namespace, plus the ``is_compiled_with_*`` probes
— all False except TPU/XLA, which is what this framework is compiled with.
"""

from __future__ import annotations

import jax

from ..core.device import (Place, device_count, get_device,  # noqa: F401
                           is_compiled_with_cuda, is_compiled_with_tpu,
                           local_devices, set_device, synchronize)
from . import cuda  # noqa: F401

__all__ = [
    "get_cudnn_version", "set_device", "get_device", "XPUPlace",
    "is_compiled_with_xpu", "is_compiled_with_cuda", "is_compiled_with_rocm",
    "is_compiled_with_npu", "is_compiled_with_tpu", "device_count",
    "synchronize", "get_all_device_type", "get_all_custom_device_type",
]


def get_cudnn_version():
    """No cuDNN in an XLA/TPU build (reference returns None when absent)."""
    return None


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def XPUPlace(dev_id=0):
    raise RuntimeError(
        "paddle_tpu is not compiled with XPU support; use set_device('tpu')")


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []
