"""Quantization: QAT + PTQ (reference: fluid/contrib/slim/quantization —
imperative/qat.py ImperativeQuantAware, post_training_quantization.py).

TPU-native design: fake-quantization is a pure jnp simulate-quantize op
with a straight-through-estimator custom_vjp (the reference's
fake_quantize_dequantize_* CUDA kernels + the identity grad registered for
them), so QAT graphs jit-compile like any other.  PTQ calibration runs the
float model while abs-max observers record ranges; ``convert`` then bakes
int8 weights + scales.  The quantized Linear matmul contracts int8×int8 →
int32 via ``preferred_element_type`` — on TPU that lands on the MXU's
native 8-bit path, which is the actual speedup story (the reference needs
MKLDNN/TensorRT engines for the same).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..nn import Layer
from ..nn import functional as F

__all__ = ["fake_quant_dequant", "AbsMaxObserver", "MovingAverageAbsMaxObserver",
           "QuantedLinear", "QuantedConv2D", "ImperativeQuantAware",
           "PostTrainingQuantization", "quant_linear_int8",
           "quant_conv2d_int8"]


# --------------------------------------------------------------------------
# fake quant with STE
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fqdq(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fqdq_fwd(x, scale, bits):
    return _fqdq(x, scale, bits), scale


def _fqdq_bwd(bits, scale, g):
    return g, jnp.zeros_like(scale)  # straight-through estimator


_fqdq.defvjp(_fqdq_fwd, _fqdq_bwd)


def fake_quant_dequant(x, scale, bits: int = 8):
    """Simulated quantize→dequantize with STE gradient (reference
    fake_quantize_dequantize_abs_max).  ``scale`` may be a scalar
    (per-tensor) or broadcastable to ``x`` (per-channel, ≙ the reference's
    channel_wise_abs_max kernels)."""
    return _fqdq(x, jnp.asarray(scale, jnp.float32), bits)


def _weight_scale(w, quantize_type: str, channel_axis: int = 0):
    """abs-max scale: scalar for per-tensor, per-channel keepdims otherwise
    (reference channel-wise quant keeps one scale per output channel)."""
    if quantize_type == "channel_wise_abs_max":
        axes = tuple(i for i in range(w.ndim) if i != channel_axis)
        return jnp.max(jnp.abs(w), axis=axes, keepdims=True).astype(jnp.float32)
    return jnp.max(jnp.abs(w)).astype(jnp.float32)


class AbsMaxObserver:
    """Running abs-max range observer (weights / PTQ activations)."""

    def __init__(self):
        self.scale = 0.0

    def observe(self, x) -> float:
        self.scale = max(self.scale, float(jnp.max(jnp.abs(x))))
        return self.scale


class MovingAverageAbsMaxObserver:
    """EMA abs-max observer (reference moving_average_abs_max, rate 0.9)."""

    def __init__(self, moving_rate: float = 0.9):
        self.rate = moving_rate
        self.scale = None

    def observe(self, x) -> float:
        cur = float(jnp.max(jnp.abs(x)))
        self.scale = cur if self.scale is None else \
            self.rate * self.scale + (1.0 - self.rate) * cur
        return self.scale


# --------------------------------------------------------------------------
# QAT layer wrappers
# --------------------------------------------------------------------------

class _QuantedBase(Layer):
    """Shared fake-quant wrapper state: weight/activation bits and the
    in-graph activation-scale buffer.

    The activation scale is a *buffer* updated in-graph (the BatchNorm
    running-stat idiom), so the EMA keeps calibrating under jitted train
    steps — a Python-side observer would bake its initial value into the
    compiled executable as a constant.
    """

    def __init__(self, inner, weight_bits, activation_bits, moving_rate,
                 weight_quantize_type, activation_quantize_type):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.w_type = weight_quantize_type
        self._rate = moving_rate if \
            activation_quantize_type == "moving_average_abs_max" else 0.0
        self.register_buffer("act_scale", Tensor(jnp.zeros([], jnp.float32)))

    def _quant_inputs(self, x):
        """Observe + fake-quant the activation; fake-quant the weight.
        Returns (xq, wq) Tensors ready for the wrapped op."""
        w = self.inner.weight
        w_scale = _weight_scale(w._data, self.w_type,
                                channel_axis=self._channel_axis(w._data))
        xd = getattr(x, "_data", x)
        prev = self.act_scale._data
        cur = jax.lax.stop_gradient(jnp.max(jnp.abs(xd)).astype(jnp.float32))
        if self.training:
            if self._rate > 0.0:
                new = jnp.where(prev == 0, cur,
                                self._rate * prev + (1 - self._rate) * cur)
            else:
                new = jnp.maximum(prev, cur)  # abs_max observer
            self.act_scale._data = new
            act_scale = new
        else:
            act_scale = jnp.where(prev == 0, cur, prev)
        xq = apply(lambda a, s: _fqdq(a, s, self.activation_bits),
                   x, Tensor(act_scale))
        wq = apply(lambda a: _fqdq(a, w_scale, self.weight_bits), w)
        return xq, wq


class QuantedLinear(_QuantedBase):
    """Linear with fake-quantized weight + activation (reference
    imperative/quant_layers QuantizedLinear)."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__(inner, weight_bits, activation_bits, moving_rate,
                         weight_quantize_type, activation_quantize_type)

    @staticmethod
    def _channel_axis(w):
        return w.ndim - 1  # Linear weight is (in, out): channel = out dim

    def forward(self, x):
        xq, wq = self._quant_inputs(x)
        return F.linear(xq, wq, self.inner.bias)


class QuantedConv2D(_QuantedBase):
    """Conv2D with fake-quantized weight + activation (reference
    imperative/quant_layers QuantizedConv2D).  Weight scales are
    per-output-channel when ``weight_quantize_type='channel_wise_abs_max'``
    (the reference's conv default)."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__(inner, weight_bits, activation_bits, moving_rate,
                         weight_quantize_type, activation_quantize_type)

    @staticmethod
    def _channel_axis(w):
        return 0  # conv weight is (out_c, in_c, kh, kw)

    def forward(self, x):
        xq, wq = self._quant_inputs(x)
        inner = self.inner
        return F.conv2d(xq, wq, inner.bias, inner._stride, inner._padding,
                        inner._dilation, inner._groups, inner._data_format)


class ImperativeQuantAware:
    """QAT entry (reference imperative/qat.py:40): walks the model and
    swaps quantizable layers for fake-quant wrappers in place."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        unsupported = set(quantizable_layer_type) - {"Linear", "Conv2D"}
        if unsupported:
            raise ValueError(
                f"quantizable_layer_type {sorted(unsupported)} not supported; "
                "only Linear and Conv2D have quant wrappers")
        self.types = tuple(quantizable_layer_type)
        self.w_type = weight_quantize_type
        self.a_type = activation_quantize_type
        self.w_bits = weight_bits
        self.a_bits = activation_bits
        self.rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for name, sub in list(model._sub_layers.items()):
            kind = type(sub).__name__
            if kind in self.types and kind == "Linear":
                model._sub_layers[name] = QuantedLinear(
                    sub, self.w_bits, self.a_bits, self.rate,
                    self.w_type, self.a_type)
            elif kind in self.types and kind == "Conv2D":
                model._sub_layers[name] = QuantedConv2D(
                    sub, self.w_bits, self.a_bits, self.rate,
                    self.w_type, self.a_type)
            else:
                self.quantize(sub)
        return model


# --------------------------------------------------------------------------
# int8 inference path
# --------------------------------------------------------------------------

def quant_linear_int8(x, w_int8, w_scale, bias=None):
    """int8 GEMM: quantize activations per-tensor, contract int8×int8→int32
    on the MXU, dequantize.  ``w_int8`` int8 (in, out); ``w_scale`` scalar."""
    qmax = 127.0
    x_scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-9)
    xq = jnp.clip(jnp.round(x / x_scale * qmax), -qmax, qmax).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, w_int8, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale / qmax) * (w_scale / qmax)
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


def quant_conv2d_int8(x, w_int8, w_scale, bias, stride, padding, dilation,
                      groups, data_format):
    """int8 conv: per-tensor activation quant, int8×int8→int32 conv (TPU MXU
    8-bit path), per-output-channel dequant (≙ the reference's
    conv2d+channel-wise dequantize MKLDNN/TRT pass)."""
    qmax = 127.0
    x_scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-9)
    xq = jnp.clip(jnp.round(x / x_scale * qmax), -qmax, qmax).astype(jnp.int8)
    from ..nn.functional.conv import _dimnums, _padding as _pad_of, _tuplize
    dn = _dimnums(2, data_format)
    acc = jax.lax.conv_general_dilated(
        xq, w_int8, window_strides=_tuplize(stride, 2),
        padding=_pad_of(padding, 2, data_format),
        rhs_dilation=_tuplize(dilation, 2), dimension_numbers=dn,
        feature_group_count=groups, preferred_element_type=jnp.int32)
    # w_scale is (out_c,) — broadcast along the output-channel dim
    c_axis = 1 if data_format[1] == "C" else acc.ndim - 1
    shape = [1] * acc.ndim
    shape[c_axis] = w_scale.shape[0]
    out = acc.astype(jnp.float32) * (x_scale / qmax) \
        * (w_scale.reshape(shape) / qmax)
    if bias is not None:
        out = out + bias.reshape(shape).astype(out.dtype)
    return out.astype(x.dtype)


class _Int8Conv2D(Layer):
    def __init__(self, w_int8, w_scale, bias, stride, padding, dilation,
                 groups, data_format):
        super().__init__()
        self.w_int8 = Tensor(w_int8)
        self.w_scale = Tensor(jnp.asarray(w_scale, jnp.float32))
        self.bias = bias
        self._conv_args = (stride, padding, dilation, groups, data_format)

    def forward(self, x):
        b = None if self.bias is None else self.bias._data
        return apply(lambda a: quant_conv2d_int8(
            a, self.w_int8._data, self.w_scale._data, b, *self._conv_args), x)


class _Int8Linear(Layer):
    def __init__(self, w_int8, w_scale, bias):
        super().__init__()
        self.w_int8 = Tensor(w_int8)
        self.w_scale = float(w_scale)
        self.bias = bias

    def forward(self, x):
        b = None if self.bias is None else self.bias._data
        return apply(lambda a: quant_linear_int8(
            a, self.w_int8._data, jnp.asarray(self.w_scale, jnp.float32), b), x)


class PostTrainingQuantization:
    """PTQ (reference post_training_quantization.py): calibrate on sample
    batches, then convert Linear layers to int8 weights + scales."""

    def __init__(self, model: Layer, algo: str = "abs_max",
                 quantizable_layer_type=("Linear", "Conv2D")):
        unsupported = set(quantizable_layer_type) - {"Linear", "Conv2D"}
        if unsupported:
            raise ValueError(
                f"quantizable_layer_type {sorted(unsupported)} not supported; "
                "only Linear and Conv2D have int8 conversions")
        self.model = model
        self.algo = algo
        self.types = tuple(quantizable_layer_type)
        self._observers: Dict[int, AbsMaxObserver] = {}

    def calibrate(self, data_loader, max_batches: Optional[int] = None):
        """Run the float model over calibration batches (observers are only
        needed for activation quant of future ops; weight scales are static)."""
        self.model.eval()
        for i, batch in enumerate(data_loader):
            if max_batches is not None and i >= max_batches:
                break
            xs = batch if isinstance(batch, (list, tuple)) else [batch]
            x = xs[0]
            self.model(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))
        return self

    def convert(self) -> Layer:
        self._convert_layer(self.model)
        return self.model

    def _convert_layer(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            kind = type(sub).__name__
            if kind in self.types and kind == "Linear":
                w = np.asarray(sub.weight._data, np.float32)
                scale = max(float(np.max(np.abs(w))), 1e-9)
                w_int8 = np.clip(np.round(w / scale * 127.0), -127, 127) \
                    .astype(np.int8)
                layer._sub_layers[name] = _Int8Linear(
                    jnp.asarray(w_int8), scale, sub.bias)
            elif kind in self.types and kind == "Conv2D":
                w = np.asarray(sub.weight._data, np.float32)  # (O, I, kh, kw)
                scale = np.maximum(np.max(np.abs(w), axis=(1, 2, 3)), 1e-9)
                w_int8 = np.clip(np.round(
                    w / scale[:, None, None, None] * 127.0), -127, 127) \
                    .astype(np.int8)
                layer._sub_layers[name] = _Int8Conv2D(
                    jnp.asarray(w_int8), scale, sub.bias, sub._stride,
                    sub._padding, sub._dilation, sub._groups,
                    sub._data_format)
            else:
                self._convert_layer(sub)
