"""Quantization: QAT + PTQ (reference: fluid/contrib/slim/quantization —
imperative/qat.py ImperativeQuantAware, post_training_quantization.py).

TPU-native design: fake-quantization is a pure jnp simulate-quantize op
with a straight-through-estimator custom_vjp (the reference's
fake_quantize_dequantize_* CUDA kernels + the identity grad registered for
them), so QAT graphs jit-compile like any other.  PTQ calibration runs the
float model while abs-max observers record ranges; ``convert`` then bakes
int8 weights + scales.  The quantized Linear matmul contracts int8×int8 →
int32 via ``preferred_element_type`` — on TPU that lands on the MXU's
native 8-bit path, which is the actual speedup story (the reference needs
MKLDNN/TensorRT engines for the same).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..nn import Layer
from ..nn import functional as F

__all__ = ["fake_quant_dequant", "AbsMaxObserver", "MovingAverageAbsMaxObserver",
           "QuantedLinear", "ImperativeQuantAware", "PostTrainingQuantization",
           "quant_linear_int8"]


# --------------------------------------------------------------------------
# fake quant with STE
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fqdq(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fqdq_fwd(x, scale, bits):
    return _fqdq(x, scale, bits), None


def _fqdq_bwd(bits, res, g):
    return g, jnp.zeros(())  # straight-through estimator


_fqdq.defvjp(_fqdq_fwd, _fqdq_bwd)


def fake_quant_dequant(x, scale, bits: int = 8):
    """Simulated quantize→dequantize with STE gradient (reference
    fake_quantize_dequantize_abs_max)."""
    return _fqdq(x, jnp.asarray(scale, jnp.float32), bits)


class AbsMaxObserver:
    """Running abs-max range observer (weights / PTQ activations)."""

    def __init__(self):
        self.scale = 0.0

    def observe(self, x) -> float:
        self.scale = max(self.scale, float(jnp.max(jnp.abs(x))))
        return self.scale


class MovingAverageAbsMaxObserver:
    """EMA abs-max observer (reference moving_average_abs_max, rate 0.9)."""

    def __init__(self, moving_rate: float = 0.9):
        self.rate = moving_rate
        self.scale = None

    def observe(self, x) -> float:
        cur = float(jnp.max(jnp.abs(x)))
        self.scale = cur if self.scale is None else \
            self.rate * self.scale + (1.0 - self.rate) * cur
        return self.scale


# --------------------------------------------------------------------------
# QAT layer wrappers
# --------------------------------------------------------------------------

class QuantedLinear(Layer):
    """Linear with fake-quantized weight + activation (reference
    imperative/quant_layers QuantizedLinear).

    The activation scale is a *buffer* updated in-graph (the BatchNorm
    running-stat idiom), so the EMA keeps calibrating under jitted train
    steps — a Python-side observer would bake its initial value into the
    compiled executable as a constant.
    """

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._rate = moving_rate if \
            activation_quantize_type == "moving_average_abs_max" else 0.0
        self.register_buffer("act_scale", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        w = self.inner.weight
        w_scale = jnp.max(jnp.abs(w._data)).astype(jnp.float32)
        xd = getattr(x, "_data", x)
        prev = self.act_scale._data
        cur = jax.lax.stop_gradient(jnp.max(jnp.abs(xd)).astype(jnp.float32))
        if self.training:
            if self._rate > 0.0:
                new = jnp.where(prev == 0, cur,
                                self._rate * prev + (1 - self._rate) * cur)
            else:
                new = jnp.maximum(prev, cur)  # abs_max observer
            self.act_scale._data = new
            act_scale = new
        else:
            act_scale = jnp.where(prev == 0, cur, prev)
        xq = apply(lambda a, s: _fqdq(a, s, self.activation_bits),
                   x, Tensor(act_scale))
        wq = apply(lambda a: _fqdq(a, w_scale, self.weight_bits), w)
        return F.linear(xq, wq, self.inner.bias)


class ImperativeQuantAware:
    """QAT entry (reference imperative/qat.py:40): walks the model and
    swaps quantizable layers for fake-quant wrappers in place."""

    def __init__(self, quantizable_layer_type=("Linear",),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        self.types = tuple(quantizable_layer_type)
        self.w_type = weight_quantize_type
        self.a_type = activation_quantize_type
        self.w_bits = weight_bits
        self.a_bits = activation_bits
        self.rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for name, sub in list(model._sub_layers.items()):
            if type(sub).__name__ in self.types:
                model._sub_layers[name] = QuantedLinear(
                    sub, self.w_bits, self.a_bits, self.rate,
                    self.w_type, self.a_type)
            else:
                self.quantize(sub)
        return model


# --------------------------------------------------------------------------
# int8 inference path
# --------------------------------------------------------------------------

def quant_linear_int8(x, w_int8, w_scale, bias=None):
    """int8 GEMM: quantize activations per-tensor, contract int8×int8→int32
    on the MXU, dequantize.  ``w_int8`` int8 (in, out); ``w_scale`` scalar."""
    qmax = 127.0
    x_scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-9)
    xq = jnp.clip(jnp.round(x / x_scale * qmax), -qmax, qmax).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, w_int8, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale / qmax) * (w_scale / qmax)
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


class _Int8Linear(Layer):
    def __init__(self, w_int8, w_scale, bias):
        super().__init__()
        self.w_int8 = Tensor(w_int8)
        self.w_scale = float(w_scale)
        self.bias = bias

    def forward(self, x):
        b = None if self.bias is None else self.bias._data
        return apply(lambda a: quant_linear_int8(
            a, self.w_int8._data, jnp.asarray(self.w_scale, jnp.float32), b), x)


class PostTrainingQuantization:
    """PTQ (reference post_training_quantization.py): calibrate on sample
    batches, then convert Linear layers to int8 weights + scales."""

    def __init__(self, model: Layer, algo: str = "abs_max",
                 quantizable_layer_type=("Linear",)):
        self.model = model
        self.algo = algo
        self.types = tuple(quantizable_layer_type)
        self._observers: Dict[int, AbsMaxObserver] = {}

    def calibrate(self, data_loader, max_batches: Optional[int] = None):
        """Run the float model over calibration batches (observers are only
        needed for activation quant of future ops; weight scales are static)."""
        self.model.eval()
        for i, batch in enumerate(data_loader):
            if max_batches is not None and i >= max_batches:
                break
            xs = batch if isinstance(batch, (list, tuple)) else [batch]
            x = xs[0]
            self.model(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))
        return self

    def convert(self) -> Layer:
        self._convert_layer(self.model)
        return self.model

    def _convert_layer(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if type(sub).__name__ in self.types:
                w = np.asarray(sub.weight._data, np.float32)
                scale = max(float(np.max(np.abs(w))), 1e-9)
                w_int8 = np.clip(np.round(w / scale * 127.0), -127, 127) \
                    .astype(np.int8)
                layer._sub_layers[name] = _Int8Linear(
                    jnp.asarray(w_int8), scale, sub.bias)
            else:
                self._convert_layer(sub)
