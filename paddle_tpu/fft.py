"""Discrete Fourier transforms (reference: python/paddle/fft.py).

TPU-native design: every transform delegates to ``jnp.fft`` — XLA lowers
FFTs to its native Fft HLO, which the TPU backend executes directly, so
there is no custom kernel to write.  The reference dispatches per-backend
C2C/R2C/C2R kernels (fft_c2c / fft_r2c / fft_c2r, python/paddle/fft.py:1357)
selected by dtype; here a single jnp call covers all of them and the r2c /
c2r distinction falls out of rfft/irfft.

Norm convention matches the reference exactly: ``"backward"`` (scale 1/n on
the inverse), ``"forward"`` (scale 1/n on the forward), ``"ortho"``
(1/sqrt(n) both ways) — the same strings jnp.fft accepts.
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be forward, backward or ortho")
    return norm


def fft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.fft(a, n=n, axis=axis, norm=norm), x)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ifft(a, n=n, axis=axis, norm=norm), x)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.rfft(a, n=n, axis=axis, norm=norm), x)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.irfft(a, n=n, axis=axis, norm=norm), x)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.hfft(a, n=n, axis=axis, norm=norm), x)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ihfft(a, n=n, axis=axis, norm=norm), x)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.fftn(a, s=s, axes=axes, norm=norm), x)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ifftn(a, s=s, axes=axes, norm=norm), x)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.rfftn(a, s=s, axes=axes, norm=norm), x)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.irfftn(a, s=s, axes=axes, norm=norm), x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    # jnp has no hfftn; compose c2c over the leading axes FIRST, then the
    # hermitian c2r over the last axis.  Order matters: hfft conjugates its
    # input, which does not commute with FFTs over other axes.
    def f(a):
        axes_ = tuple(range(a.ndim)) if axes is None else tuple(axes)
        lead, last = axes_[:-1], axes_[-1]
        if lead:
            slead = None if s is None else s[:-1]
            a = jnp.fft.fftn(a, s=slead, axes=lead, norm=norm)
        nlast = None if s is None else s[-1]
        return jnp.fft.hfft(a, n=nlast, axis=last, norm=norm)
    return apply(f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    def f(a):
        axes_ = tuple(range(a.ndim)) if axes is None else tuple(axes)
        lead, last = axes_[:-1], axes_[-1]
        nlast = None if s is None else s[-1]
        out = jnp.fft.ihfft(a, n=nlast, axis=last, norm=norm)
        if lead:
            slead = None if s is None else s[:-1]
            out = jnp.fft.ifftn(out, s=slead, axes=lead, norm=norm)
        return out
    return apply(f, x)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=axes, norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .core.dtype import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .core.dtype import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x)
