"""Device / place management.

TPU-native analog of the reference's Place / DeviceContextPool
(paddle/fluid/platform/place.h, device_context.h).  On TPU+XLA there are no
streams or contexts to manage — this module owns device discovery, the
current-device notion, and host/device transfer helpers.
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional

import jax


def local_devices(platform: Optional[str] = None):
    """Devices of the requested platform, honoring ``PADDLE_TPU_PLATFORM``.

    Some PJRT plugins register themselves as the default platform regardless of
    ``JAX_PLATFORMS``; tests that need the virtual 8-device CPU mesh set
    ``PADDLE_TPU_PLATFORM=cpu`` to force device discovery onto it.
    """
    platform = platform or os.environ.get("PADDLE_TPU_PLATFORM")
    if platform:
        try:
            return jax.devices(platform)
        except RuntimeError as e:
            import warnings
            warnings.warn(f"requested platform {platform!r} unavailable "
                          f"({e}); falling back to default platform")
    return jax.devices()


class Place:
    """String-y device handle (``paddle.CUDAPlace``-family parity).

    Accepts ``"tpu"``, ``"tpu:0"``, ``"cpu"``, ``"gpu:1"``.
    """

    def __init__(self, spec: str = "tpu:0"):
        if ":" in spec:
            kind, idx = spec.split(":")
            self.kind, self.index = kind, int(idx)
        else:
            self.kind, self.index = spec, 0

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        other = Place(other) if isinstance(other, str) else other
        return (self.kind, self.index) == (other.kind, other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def is_cpu_place(self):
        return self.kind == "cpu"

    def jax_device(self):
        devs = _devices_of_kind(self.kind)
        return devs[self.index % len(devs)]


_current: Optional[Place] = None


@functools.lru_cache(maxsize=None)
def _platform_names() -> List[str]:
    return [d.platform for d in jax.devices()]


def _devices_of_kind(kind: str):
    if kind == "cpu":
        return local_devices("cpu")
    # "tpu"/"gpu"/"xpu" → default platform accelerators
    return jax.devices()


def set_device(spec: str) -> Place:
    """``paddle.set_device`` parity."""
    global _current
    _current = Place(spec) if isinstance(spec, str) else spec
    return _current


def get_device() -> str:
    """``paddle.get_device`` parity — returns e.g. ``"tpu:0"``."""
    p = _get_place()
    return f"{p.kind}:{p.index}"


def _get_place() -> Place:
    global _current
    if _current is None:
        plat = jax.default_backend()
        _current = Place(f"{plat}:0")
    return _current


def device_count() -> int:
    """Number of local accelerator devices (``paddle.device.cuda.device_count`` parity)."""
    return jax.local_device_count()


def is_compiled_with_cuda() -> bool:  # API parity; always False on TPU builds
    return False


def is_compiled_with_tpu() -> bool:
    return jax.default_backend() == "tpu"


def synchronize() -> None:
    """Block until all dispatched work completes (``paddle.device.synchronize``)."""
    (jax.device_put(0.0) + 0).block_until_ready()
