"""Eager Tensor wrapper + op dispatch.

TPU-native analog of the reference's VarBase (pybind/imperative.cc:877) and
the generated ``core.ops.*`` fast path (pybind/op_function_generator.cc):
every eager op funnels through :func:`apply`, which unwraps Tensors to
``jax.Array``, runs the pure jnp function, wraps outputs, and records an
autograd Node when gradients are required (tracer.cc:241 CreateGradOpNode
semantics).

Inside a ``jax.jit`` trace the same layer code runs on raw tracers with zero
wrapper overhead — the dual-paradigm split of the reference (dygraph/static)
becomes "wrapped-eager / traced-functional" here.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, dtype as dtype_mod, flags
from .autograd import Node

_is_tensor_leaf = lambda x: isinstance(x, Tensor)


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class Tensor:
    """Eager tensor: a ``jax.Array`` plus autograd metadata."""

    __array_priority__ = 100.0
    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "name", "_hooks",
                 "trainable", "is_leaf_param", "_consumers", "__weakref__", "__dict__")

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node: Optional[Node] = None
        self.name = name
        self._hooks = {}
        self._consumers = []
        self.trainable = not stop_gradient

    # -- basic introspection ------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        from . import device
        return device._get_place()

    @property
    def T(self):
        return apply(jnp.transpose, self)

    def numel(self):
        return self.size

    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return np.asarray(self._data).item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __jax_array__(self):
        return self._data

    def __repr__(self):
        grad_note = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_note},\n"
                f"       {np.asarray(self._data) if not self._is_traced() else self._data!r})")

    def _is_traced(self):
        return isinstance(self._data, jax.core.Tracer)

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        # Without this, iteration falls back to __getitem__ with unbounded
        # indices, which never raises (XLA gather clamps out-of-range) and
        # spins forever.  Paddle iterates over the leading dim.
        return (self[i] for i in range(len(self)))

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor(self._grad)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else _unwrap(value)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def _accumulate_grad(self, ct):
        if ct.dtype != self._data.dtype:
            ct = ct.astype(self._data.dtype)
        self._grad = ct if self._grad is None else self._grad + ct

    def register_hook(self, hook: Callable):
        hid = len(self._hooks)
        self._hooks[hid] = hook

        class _Removable:
            def remove(self_):
                self._hooks.pop(hid, None)

        return _Removable()

    def detach(self):
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def clone(self):
        return apply(lambda x: x + 0, self)

    def _adopt(self, produced: "Tensor"):
        """Take over ``produced``'s value and graph position (in-place ops).

        If ``self`` already participates in the graph (as an input of any
        recorded node, including ``produced``'s), a detached stand-in keeps
        the pre-mutation value and graph position so backward sees the
        correct primal (no self-loop, no post-mutation value leaking into
        earlier consumers).
        """
        import weakref
        node = produced._node
        consumers = [r for r in self._consumers if r() is not None]
        if consumers:
            old = Tensor(self._data, stop_gradient=self.stop_gradient)
            old._node = self._node
            old._consumers = consumers
            if self._node is not None:
                for i, ref in enumerate(self._node.out_refs):
                    if ref() is self:
                        self._node.out_refs[i] = weakref.ref(old)
            for r in consumers:
                n = r()
                if n is not None:
                    n.diff_inputs = [old if t is self else t for t in n.diff_inputs]
        if node is not None:
            for i, ref in enumerate(node.out_refs):
                if ref() is produced:
                    node.out_refs[i] = weakref.ref(self)
        self._data = produced._data
        self._node = node
        self._consumers = []
        return self

    # -- mutation / conversion ---------------------------------------------
    def set_value(self, value):
        value = _unwrap(value)
        self._data = jnp.asarray(value).astype(self._data.dtype).reshape(self._data.shape)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def astype(self, dt):
        dt = dtype_mod.convert_dtype(dt)
        return apply(lambda x: x.astype(dt), self)

    def cast(self, dt):
        return self.astype(dt)

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "xpu"):
                continue
            return self.astype(a)
        if "dtype" in kwargs:
            return self.astype(kwargs["dtype"])
        return self

    def pin_memory(self):
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        return apply(lambda x, i: x[i], self, idx)

    def __setitem__(self, idx, value):
        produced = apply(lambda x, i, v: x.at[i].set(v), self, idx, value)
        self._adopt(produced)

    # -- operators (full set patched in paddle_tpu.tensor.__init__) --------
    def __neg__(self):
        return apply(jnp.negative, self)

    def __abs__(self):
        return apply(jnp.abs, self)

    def __add__(self, o):
        return apply(jnp.add, self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return apply(jnp.subtract, self, o)

    def __rsub__(self, o):
        return apply(jnp.subtract, o, self)

    def __mul__(self, o):
        return apply(jnp.multiply, self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return apply(jnp.true_divide, self, o)

    def __rtruediv__(self, o):
        return apply(jnp.true_divide, o, self)

    def __floordiv__(self, o):
        return apply(jnp.floor_divide, self, o)

    def __rfloordiv__(self, o):
        return apply(jnp.floor_divide, o, self)

    def __pow__(self, o):
        return apply(jnp.power, self, o)

    def __rpow__(self, o):
        return apply(jnp.power, o, self)

    def __mod__(self, o):
        return apply(jnp.mod, self, o)

    def __rmod__(self, o):
        return apply(jnp.mod, o, self)

    def __matmul__(self, o):
        return apply(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return apply(jnp.matmul, o, self)

    def __lt__(self, o):
        return apply(jnp.less, self, o)

    def __le__(self, o):
        return apply(jnp.less_equal, self, o)

    def __gt__(self, o):
        return apply(jnp.greater, self, o)

    def __ge__(self, o):
        return apply(jnp.greater_equal, self, o)

    def __eq__(self, o):
        if o is None:
            return False
        return apply(jnp.equal, self, o)

    def __ne__(self, o):
        if o is None:
            return True
        return apply(jnp.not_equal, self, o)

    def __and__(self, o):
        return apply(jnp.logical_and if self.dtype == jnp.bool_ else jnp.bitwise_and, self, o)

    def __or__(self, o):
        return apply(jnp.logical_or if self.dtype == jnp.bool_ else jnp.bitwise_or, self, o)

    def __xor__(self, o):
        return apply(jnp.logical_xor if self.dtype == jnp.bool_ else jnp.bitwise_xor, self, o)

    def __invert__(self):
        return apply(jnp.logical_not if self.dtype == jnp.bool_ else jnp.bitwise_not, self)


class Parameter(Tensor):
    """Trainable tensor (reference: ParamBase framework.py:6042)."""

    def __init__(self, data, name: Optional[str] = None, trainable: bool = True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.is_leaf_param = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


_EAGER_STREAK = [0]  # grad-recording eager dispatches since the last jit


def note_compiled_call():
    """Reset the eager-nudge streak: called by every compiled-step wrapper
    (jit/functional steps, StaticFunction) on EVERY invocation — cache hits
    included, which dispatch zero eager ops and would otherwise never reset
    the counter, nudging users who already follow the advice."""
    if _EAGER_STREAK[0] > 0:
        _EAGER_STREAK[0] = 0


def _nudge_eager_loop(traced: bool, record: bool):
    """One-time perf nudge for training loops ground out op-by-op (the
    reference nudges dygraph users toward static the same way): each eager
    dispatch is a separate host->device round-trip, while the supported
    training path compiles the whole step.  Counting only grad-recording
    dispatches keeps inference/debug scripting quiet; the streak resets on
    any traced dispatch (tracing time) and on every compiled-step call
    (note_compiled_call)."""
    limit = flags.flag("FLAGS_eager_nudge_after")
    if limit <= 0 or _EAGER_STREAK[0] < 0:  # disabled / already warned
        return
    if traced:
        _EAGER_STREAK[0] = 0
        return
    if not record:
        return
    _EAGER_STREAK[0] += 1
    if _EAGER_STREAK[0] >= limit:
        import os
        import sys
        import warnings
        # point the warning at the user's loop, not a paddle_tpu wrapper:
        # walk out of the package so file:line (and the once-per-location
        # filter key) land in user code
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        level, f = 2, sys._getframe(1)
        while f.f_back is not None and \
                f.f_code.co_filename.startswith(pkg + os.sep):
            f = f.f_back
            level += 1
        warnings.warn(
            f"{limit} consecutive eagerly-dispatched ops recorded gradients "
            "without any jit-compiled step. Eager mode is the debugging "
            "surface; for training speed wrap the step in paddle.jit."
            "make_train_step / @paddle.jit.to_static or use hapi Model.fit "
            "(set FLAGS_eager_nudge_after=0 to silence).",
            UserWarning, stacklevel=level)
        _EAGER_STREAK[0] = -1  # warn once per process


def apply(fn: Callable, *args, name: Optional[str] = None, **kwargs) -> Any:
    """Dispatch one eager op (the ``TraceOp`` analog).

    ``fn`` must be a pure, jax-traceable function of arrays; Tensor leaves
    anywhere in ``args``/``kwargs`` are unwrapped.  Outputs are wrapped back
    into Tensors; a grad Node is recorded when needed.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor_leaf)
    tensor_positions = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

    if not tensor_positions:
        return fn(*args, **kwargs)

    raw_leaves = [_unwrap(l) for l in leaves]
    traced = any(isinstance(raw_leaves[i], jax.core.Tracer) for i in tensor_positions)

    # AMP list-driven dispatch (reference amp_auto_cast.cc white/black lists):
    # white ops cast float inputs to the amp dtype, black ops promote 16-bit
    # floats to fp32.  The cast map is baked into the node's rebuild so
    # backward replay is dtype-identical regardless of ambient state.
    import sys as _sys
    amp_cast_map = {}
    amp_mod = _sys.modules.get("paddle_tpu.amp")
    if amp_mod is not None and amp_mod.amp_enabled():
        _st = amp_mod.amp_state()
        _opname = (name or getattr(fn, "__name__", "")).lower()
        if _opname in _st.white:
            for i in tensor_positions:
                rl = raw_leaves[i]
                if jnp.issubdtype(rl.dtype, jnp.floating) and rl.dtype != _st.dtype:
                    amp_cast_map[i] = _st.dtype
        elif _opname in _st.black:
            for i in tensor_positions:
                rl = raw_leaves[i]
                if rl.dtype in (jnp.float16, jnp.bfloat16):
                    amp_cast_map[i] = jnp.float32
        for i, dt in amp_cast_map.items():
            raw_leaves[i] = raw_leaves[i].astype(dt)

    diff_positions = [
        i for i in tensor_positions
        if not leaves[i].stop_gradient and jnp.issubdtype(raw_leaves[i].dtype, jnp.floating)
    ]
    record = (not traced) and autograd.is_grad_enabled() and bool(diff_positions)

    rargs, rkwargs = jax.tree_util.tree_unflatten(treedef, raw_leaves)
    out_raw = fn(*rargs, **rkwargs)

    if flags.flag("FLAGS_eager_log_ops"):
        print(f"[eager] {name or getattr(fn, '__name__', fn)}")
    _nudge_eager_loop(traced, record)
    if flags.flag("FLAGS_benchmark") and not traced:
        jax.block_until_ready(out_raw)

    is_arr = lambda x: isinstance(x, (jax.Array, jax.core.Tracer, np.ndarray, np.generic))
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out_raw)

    if flags.flag("FLAGS_check_nan_inf") and not traced:
        for ol in out_leaves:
            if is_arr(ol) and jnp.issubdtype(jnp.asarray(ol).dtype, jnp.floating):
                if not bool(jnp.isfinite(ol).all()):
                    raise FloatingPointError(
                        f"NaN/Inf in output of {name or getattr(fn, '__name__', fn)}")

    node = None
    if record:
        diff_tensors = [leaves[i] for i in diff_positions]
        const_leaves = list(raw_leaves)
        diff_out_positions = [
            i for i, ol in enumerate(out_leaves)
            if is_arr(ol) and jnp.issubdtype(jnp.asarray(ol).dtype, jnp.floating)
        ]

        def rebuild(*primals):
            cl = list(const_leaves)
            for pos, p in zip(diff_positions, primals):
                cl[pos] = p if pos not in amp_cast_map \
                    else p.astype(amp_cast_map[pos])
            a, k = jax.tree_util.tree_unflatten(treedef, cl)
            o = fn(*a, **k)
            ols = jax.tree_util.tree_leaves(o)
            return tuple(ols[i] for i in diff_out_positions)

        ctx_factory = None
        if amp_mod is not None:
            # snapshot even when amp is OFF — backward may run inside a later
            # auto_cast block and must replay with the recorded (off) state
            ctx_factory = amp_mod.capture_autocast()
        node = Node(rebuild, diff_tensors, name=name or getattr(fn, "__name__", "op"),
                    ctx_factory=ctx_factory)
        import weakref as _weakref
        nref = _weakref.ref(node)
        for t in diff_tensors:
            t._consumers.append(nref)

    wrapped = []
    di = 0
    diff_out_set = set(diff_out_positions) if record else set()
    for i, ol in enumerate(out_leaves):
        if is_arr(ol):
            t = Tensor(ol, stop_gradient=not (record and i in diff_out_set))
            if record and i in diff_out_set:
                node.add_output(t)
                t._node = node
            wrapped.append(t)
        else:
            wrapped.append(ol)
        di += 1
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """``paddle.to_tensor`` parity."""
    if isinstance(data, Tensor):
        d = data._data
    else:
        d = data
    dt = dtype_mod.convert_dtype(dtype)
    if isinstance(d, (jax.Array, jax.core.Tracer)):
        arr = d.astype(dt) if dt is not None and d.dtype != dt else d
    else:
        arr = jnp.asarray(d, dtype=dt) if dt is not None else _default_convert(d)
    return Tensor(arr, stop_gradient=stop_gradient)


def _default_convert(d):
    arr = np.asarray(d)
    if arr.dtype == np.float64:
        arr = arr.astype(dtype_mod.get_default_dtype())
    return jnp.asarray(arr)
