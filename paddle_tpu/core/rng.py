"""Random number state.

Design: JAX is functional (explicit PRNG keys), the reference is stateful
(global + per-parallel-layer seed trackers, fleet/meta_parallel/parallel_layers/random.py).
We bridge with named *streams*:

- Eager mode: each stream owns a key that is split on every draw.
- Traced (jit) mode: a ``rng_scope(key)`` context installs a traced base key;
  draws fold in a per-trace counter, so randomness is a pure function of the
  scope key and the (deterministic) draw order.  Passing a fresh key per step
  gives fresh dropout masks without retracing.

``RNGSequenceTracker`` reproduces the reference's model-parallel RNG contract:
the ``global_seed`` stream is identical across model-parallel ranks while
``local_seed`` differs per rank (dropout inside sharded layers must differ).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax
import numpy as np

from . import flags


class _Stream:
    def __init__(self, seed: int):
        self.seed = seed
        self.key = jax.random.key(seed)

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


class _ScopeState(threading.local):
    def __init__(self):
        self.key = None
        self.counter = 0


_scope = _ScopeState()
_streams: Dict[str, _Stream] = {}


def seed(value: int) -> None:
    """``paddle.seed`` parity: reseed every stream deterministically."""
    flags.set_flags({"FLAGS_seed": int(value)})
    _streams.clear()
    _streams["global"] = _Stream(int(value))


def get_stream(name: str = "global") -> _Stream:
    if name not in _streams:
        base = int(flags.flag("FLAGS_seed"))
        offset = np.uint32(abs(hash(name)) % (2**31))
        _streams[name] = _Stream(base + int(offset))
    return _streams[name]


def add_stream(name: str, seed_value: int) -> None:
    _streams[name] = _Stream(int(seed_value))


@contextlib.contextmanager
def rng_scope(key, stream: Optional[str] = None):
    """Install a traced base key; inside jit all draws derive from it."""
    prev_key, prev_counter = _scope.key, _scope.counter
    _scope.key, _scope.counter = key, 0
    try:
        yield
    finally:
        _scope.key, _scope.counter = prev_key, prev_counter


def next_key(stream: str = "global"):
    """Draw a PRNG key: scope-derived when inside ``rng_scope``, else stateful."""
    if _scope.key is not None:
        _scope.counter += 1
        return jax.random.fold_in(_scope.key, _scope.counter)
    return get_stream(stream).next_key()


def in_rng_scope() -> bool:
    return _scope.key is not None


class RNGSequenceTracker:
    """Model-parallel RNG state tracker (reference: parallel_layers/random.py).

    ``get_rng_state_tracker().rng_state("local_seed")`` scopes draws to a
    rank-dependent stream so dropout differs across TP ranks; the default
    ``global_seed`` stream matches across ranks.
    """

    def __init__(self):
        self.seeds = {}

    def add(self, name: str, seed_value: int):
        if name in self.seeds and self.seeds[name] != seed_value:
            raise ValueError(f"seed for {name} already set to {self.seeds[name]}")
        self.seeds[name] = seed_value
        add_stream(name, seed_value)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self.seeds and name not in _streams:
            self.add(name, int(flags.flag("FLAGS_seed")) + abs(hash(name)) % (2**31))
        if _scope.key is not None:
            # Traced mode: fold the stream name into the scope key so streams
            # stay decorrelated but remain pure functions of the step key.
            sub = jax.random.fold_in(_scope.key, abs(hash(name)) % (2**31))
            with rng_scope(sub):
                yield
        else:
            prev = _scope.key
            assert prev is None
            stream = get_stream(name)
            try:
                _streams["global"], _streams[f"__saved_global"] = stream, _streams.get("global", get_stream("global"))
                yield
            finally:
                _streams["global"] = _streams.pop("__saved_global")


_tracker = RNGSequenceTracker()


def get_rng_state_tracker() -> RNGSequenceTracker:
    return _tracker


def get_rng_state():
    """``paddle.get_rng_state``-ish: returns the raw key data per stream."""
    return {name: jax.random.key_data(s.key) for name, s in _streams.items()}


def set_rng_state(state) -> None:
    for name, data in state.items():
        st = get_stream(name)
        st.key = jax.random.wrap_key_data(np.asarray(data))
