"""Dtype system.

Mirrors the reference's VarType dtype enum surface
(paddle/fluid/framework/framework.proto:117) as thin aliases over JAX dtypes.
TPU-first: bfloat16 is a first-class citizen.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import flags

# Canonical dtype objects (exposed as paddle_tpu.float32 etc.)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
bool = jnp.bool_  # noqa: A001 - paddle exposes paddle.bool
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
}

FLOATING = (float16, bfloat16, float32, float64)
INTEGER = (int8, int16, int32, int64, uint8, uint16, uint32, uint64)


_X64_NARROW = {"int64": "int32", "uint64": "uint32", "float64": "float32",
               "complex128": "complex64"}


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize str/np/jnp dtype specs to a jnp dtype.

    With x64 disabled (the TPU default), 64-bit specs are mapped to their
    32-bit siblings explicitly — identical to JAX's silent truncation but
    without the per-call UserWarning, and visible here as policy."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        dt = jnp.dtype(_ALIASES[key] if key in _ALIASES else key)
    else:
        dt = jnp.dtype(dtype)
    if not jax.config.jax_enable_x64 and dt.name in _X64_NARROW:
        dt = jnp.dtype(_X64_NARROW[dt.name])
    return dt


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


def get_default_dtype():
    """``paddle.get_default_dtype`` parity."""
    return convert_dtype(flags.flag("FLAGS_default_dtype"))


def set_default_dtype(d) -> None:
    """``paddle.set_default_dtype`` parity."""
    d = convert_dtype(d)
    if not jnp.issubdtype(d, jnp.floating):
        raise TypeError(f"default dtype must be floating point, got {d}")
    flags.set_flags({"FLAGS_default_dtype": np.dtype(d).name if d != bfloat16 else "bfloat16"})
