"""Eager autograd engine.

TPU-native replacement for the reference's imperative tape
(paddle/fluid/imperative/tracer.cc:146 TraceOp + basic_engine.cc:382
BasicEngine::Execute).  Instead of per-op C++ grad nodes, each dispatched op
records a ``Node`` carrying the op's pure function and its inputs; backward
walks the node graph in reverse topological order and uses ``jax.vjp`` per
node to produce cotangents.  Gradients accumulate into leaf ``Tensor.grad``
(GradientAccumulator semantics).

Under ``jax.jit`` tracing nothing is recorded — the functional path
(``jax.grad`` over the extracted parameter pytree) is the performant route,
mirroring dygraph-vs-static in the reference.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()

# one-shot perf nudge after many un-jitted train steps (≙ the reference's
# dygraph->static guidance); tests stay well under the threshold
_EAGER_STEPS = 0
_EAGER_WARN_AT = 500
_EAGER_WARNED = False


def is_grad_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def no_grad():
    """``paddle.no_grad`` parity."""
    prev = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = prev


def set_grad_enabled(mode: bool):
    class _Ctx:
        def __enter__(self_):
            self_.prev = _state.enabled
            _state.enabled = bool(mode)
            return self_

        def __exit__(self_, *exc):
            _state.enabled = self_.prev
            return False

    return _Ctx()


class Node:
    """One recorded eager op: reconstructable pure function + inputs."""

    __slots__ = ("rebuild", "diff_inputs", "out_refs", "name", "ctx_factory",
                 "__weakref__")

    def __init__(self, rebuild: Callable, diff_inputs: Sequence, name: str = "op",
                 ctx_factory: Optional[Callable] = None):
        # rebuild(*input_datas) -> tuple of differentiable raw outputs
        self.rebuild = rebuild
        self.diff_inputs = list(diff_inputs)  # Tensors we differentiate w.r.t.
        # guarded-by: none (autograd tapes are built and walked on one
        # thread; pool-task label is unique-name over-approximation)
        self.out_refs: List[weakref.ref] = []  # weakrefs to output Tensors
        self.name = name
        # re-installs ambient dispatch state (e.g. amp autocast) so backward's
        # vjp replay reproduces the recorded forward exactly
        self.ctx_factory = ctx_factory

    def add_output(self, tensor) -> int:
        self.out_refs.append(weakref.ref(tensor))
        return len(self.out_refs) - 1


def _toposort(root_node: Node) -> List[Node]:
    order: List[Node] = []
    seen = set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.diff_inputs:
            if t._node is not None and id(t._node) not in seen:
                stack.append((t._node, False))
    return order  # children before parents; reverse-exec order is reversed(order)... see below


def backward(tensor, grad=None, retain_graph: bool = False, capture=None,
             accumulate_leaves: bool = True) -> None:
    """Run reverse-mode from ``tensor`` accumulating into leaf ``.grad``.

    ``capture``: optional dict id(Tensor)->Tensor — cotangents for these
    tensors (leaf or not) are written to their ``.grad`` (used by
    ``paddle.grad``).  ``accumulate_leaves=False`` suppresses writing any
    other leaf's ``.grad`` (so ``paddle.grad`` doesn't corrupt pending
    parameter gradients).
    """
    global _EAGER_STEPS, _EAGER_WARNED
    _EAGER_STEPS += 1
    # >= with a sticky flag, not ==: concurrent increments may skip the
    # exact trigger value (worst case under a race is a duplicate warning,
    # never a lost one)
    if _EAGER_STEPS >= _EAGER_WARN_AT and not _EAGER_WARNED:
        _EAGER_WARNED = True
        import warnings
        warnings.warn(
            f"{_EAGER_WARN_AT} eager backward() passes in this process: "
            "per-op Python dispatch dominates un-jitted training loops on "
            "TPU. Wrap the train step with paddle.jit.to_static / "
            "jit_train_step (the dygraph->static nudge, reference "
            "dygraph/base.py).", stacklevel=2)
    if grad is None:
        if tensor.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad")
        grad = jnp.ones_like(tensor._data)
    else:
        grad = getattr(grad, "_data", grad)
    if tensor._node is None:
        return  # constant w.r.t. everything recorded

    cot: Dict[int, Any] = {id(tensor): grad}
    keep = {id(tensor): tensor}  # keep tensors alive while walking

    order = _toposort(tensor._node)
    # ``order`` has producers before consumers; execute in reverse.
    for node in reversed(order):
        out_cots = []
        any_ct = False
        outs = [r() for r in node.out_refs]
        for o in outs:
            if o is not None and id(o) in cot:
                out_cots.append(cot[id(o)])
                any_ct = True
            else:
                out_cots.append(None)
        if not any_ct:
            continue
        primals = [t._data for t in node.diff_inputs]
        ctx = node.ctx_factory() if node.ctx_factory is not None \
            else contextlib.nullcontext()
        with ctx:
            raw_outs, vjp_fn = jax.vjp(node.rebuild, *primals)
        filled = tuple(
            ct if ct is not None else jnp.zeros_like(ro)
            for ct, ro in zip(out_cots, raw_outs))
        in_cots = vjp_fn(filled)
        for t, ct in zip(node.diff_inputs, in_cots):
            if t.stop_gradient:
                continue
            if t._hooks:
                for h in t._hooks.values():
                    out = h(ct)
                    if out is not None:
                        ct = getattr(out, "_data", out)
            if capture is not None and id(t) in capture:
                t._accumulate_grad(ct)
            if t._node is None:  # leaf: accumulate into .grad
                if accumulate_leaves and (capture is None or id(t) not in capture):
                    t._accumulate_grad(ct)
            else:
                key = id(t)
                keep[key] = t
                cot[key] = ct if key not in cot else cot[key] + ct
        if not retain_graph:
            node.out_refs = [r for r in node.out_refs]  # keep structure; graph freed via tensor GC

    if not retain_graph:
        # Free the graph: detach every tensor reachable in this pass.
        for node in order:
            for r in node.out_refs:
                o = r()
                if o is not None:
                    o._node = None
