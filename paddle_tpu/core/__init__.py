from . import autograd, device, dtype, flags, rng  # noqa: F401
from .autograd import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .device import Place, get_device, set_device, synchronize  # noqa: F401
from .dtype import convert_dtype, get_default_dtype, set_default_dtype  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .rng import get_rng_state, get_rng_state_tracker, seed, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, apply, to_tensor  # noqa: F401
