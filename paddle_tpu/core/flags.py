"""Global flag registry.

TPU-native analog of the reference's gflags tier
(paddle/fluid/platform/flags.cc:48+, pybind/global_value_getter_setter.cc) and
``paddle.set_flags``/``get_flags``.  One flat dict, seeded from ``FLAGS_*``
environment variables at import, mutable at runtime.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Mapping, Optional

_REGISTRY: Dict[str, "Flag"] = {}


class Flag:
    __slots__ = ("name", "value", "default", "help")

    def __init__(self, name: str, default: Any, help: str = ""):
        self.name = name
        self.default = default
        self.value = default
        self.help = help


def define_flag(name: str, default: Any, help: str = "") -> None:
    if name in _REGISTRY:
        return
    flag = Flag(name, default, help)
    env = os.environ.get(name)
    if env is not None:
        flag.value = _coerce(env, default)
    _REGISTRY[name] = flag


def _coerce(text: str, like: Any) -> Any:
    if isinstance(like, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(like, int):
        return int(text)
    if isinstance(like, float):
        return float(text)
    return text


def set_flags(flags: Mapping[str, Any]) -> None:
    """Set one or more registered flags (``paddle.set_flags`` parity)."""
    for name, value in flags.items():
        if name not in _REGISTRY:
            define_flag(name, value)
        else:
            _REGISTRY[name].value = value


def get_flags(names: Optional[Iterable[str]] = None) -> Dict[str, Any]:
    """Read flags (``paddle.get_flags`` parity)."""
    if names is None:
        return {k: f.value for k, f in _REGISTRY.items()}
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n].value for n in names}


def flag(name: str) -> Any:
    return _REGISTRY[name].value


# Core flags (subset of the reference's 51 exported flags that are meaningful
# on TPU; the CUDA/cuDNN knobs have no analog).
define_flag("FLAGS_check_nan_inf", False, "Check every op output for NaN/Inf (eager mode).")
define_flag("FLAGS_use_pallas_kernels", True, "Use Pallas fused kernels where available.")
define_flag("FLAGS_allocator_strategy", "xla", "Kept for API parity; XLA owns allocation on TPU.")
define_flag("FLAGS_default_dtype", "float32", "Default floating point dtype.")
define_flag("FLAGS_seed", 0, "Global random seed.")
define_flag("FLAGS_eager_log_ops", False, "Log every eagerly dispatched op (debug tracing).")
define_flag("FLAGS_benchmark", False, "Block on every eager op result (perf debugging).")
define_flag("FLAGS_eager_nudge_after", 20000,
            "Warn once after this many consecutive grad-recording eager "
            "dispatches with no jit step (0 disables).")
define_flag("FLAGS_use_fused_ln", False,
            "Route LN+residual+dropout through the Pallas kernel (ops/fused.py); "
            "off by default — flip only where tools/fused_probe.py shows XLA "
            "leaving step time on the table.")
define_flag("FLAGS_paged_attn_interpret", False,
            "Run the paged-attention decode kernel in Pallas interpret "
            "mode (CPU CI of the in-kernel table walk).")
define_flag("FLAGS_fused_ln_interpret", False,
            "Allow the fused-LN Pallas kernel in interpret mode off-TPU (tests).")
define_flag("FLAGS_use_fused_adamw", False,
            "Reserved for the flat fused AdamW sweep (ops/fused.py:"
            "fused_adamw_flat — kernel shipped + tested; tree-level wiring "
            "lands only if tools/fused_probe.py shows XLA's own fusion of the "
            "update chain leaving >5% step time).")
