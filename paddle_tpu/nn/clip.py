"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def _clip_eager(self, params, grads: dict) -> dict:
        raise NotImplementedError

    def _clip_pytree(self, grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        # static-graph style [(param, grad)] interface
        out = []
        for p, g in params_grads:
            out.append((p, g))
        return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip_eager(self, params, grads):
        return {k: (None if g is None else jnp.clip(g, self.min, self.max))
                for k, g in grads.items()}

    def _clip_pytree(self, grads):
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def _clip_eager(self, params, grads):
        return {k: (None if g is None else self._one(g)) for k, g in grads.items()}

    def _clip_pytree(self, grads):
        return jax.tree_util.tree_map(self._one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip — the hybrid-parallel critical one (reference:
    clip.py ClipGradByGlobalNorm + hybrid_parallel_optimizer.py
    HybridParallelClipGrad which psums the squared norm across mesh axes)."""

    def __init__(self, clip_norm, group_name="default_group", axes=None):
        self.clip_norm = float(clip_norm)
        self.axes = axes  # mesh axes to reduce over inside pjit (set by fleet)

    def _global_norm(self, leaves):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        if self.axes:
            # inside shard_map: sum partial norms across model-parallel axes
            for ax in self.axes:
                sq = jax.lax.psum(sq, ax)
        return jnp.sqrt(sq)

    def _clip_eager(self, params, grads):
        leaves = [g for g in grads.values() if g is not None]
        if not leaves:
            return grads
        gnorm = self._global_norm(leaves)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return {k: (None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype))
                for k, g in grads.items()}

    def _clip_pytree(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = self._global_norm(leaves)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)
