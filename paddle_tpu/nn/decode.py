"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode
(reference: python/paddle/nn/decode.py — Decoder/BeamSearchDecoder:110,
dynamic_decode; C++ twin gather_tree_op).

TPU-native shape: beams are a static axis folded into the batch
(B*K rows through the cell — one big MXU matmul instead of K small ones);
the step loop is host-side Python with device-resident state (eager mode —
the decode length is data-dependent via early-exit, which the reference
also runs host-side), and the final backtrace is the device-side
``gather_tree`` scan.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .functional.extras import gather_tree
from .layer.base import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract step-decoder interface (the contract ``dynamic_decode``
    drives; reference decode.py Decoder, with the parent-pointer addition
    the beam decoder needs for the device-side backtrace):

    - ``initialize(inits) -> (first_inputs, states)``
    - ``step(time, inputs, states, **kwargs) -> (outputs, states, parents)``
      (``parents`` may be None for non-beam decoders; ``kwargs`` are the
      extra arguments passed through ``dynamic_decode``)
    - ``finalize(step_outputs, step_parents, final_states)
      -> (outputs, final_states)``
    """

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, step_outputs, step_parents, final_states):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference decode.py:110).

    cell: an RNNCell-like layer ``cell(inputs, states) -> (out, new_states)``;
    ``output_fn`` maps cell output to vocab logits; ``embedding_fn`` maps
    token ids to embeddings.
    """

    def __init__(self, cell, start_token: int, end_token: int, beam_size: int,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers (reference static methods) ---------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) -> (B*K, ...) by repeating each row K times."""
        raw = getattr(x, "_data", x)
        tiled = jnp.repeat(raw, beam_size, axis=0)
        return Tensor(tiled) if isinstance(x, Tensor) else tiled

    def _merge(self, x):   # (B, K, ...) -> (B*K, ...)
        return x.reshape((-1,) + x.shape[2:])

    def _split(self, x, B):  # (B*K, ...) -> (B, K, ...)
        return x.reshape((B, self.beam_size) + x.shape[1:])

    # -- Decoder interface --------------------------------------------------
    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(
            lambda s: jnp.repeat(getattr(s, "_data", s), self.beam_size,
                                 axis=0),
            initial_cell_states)
        some = jax.tree_util.tree_leaves(states)[0]
        B = some.shape[0] // self.beam_size
        ids = jnp.full((B, self.beam_size), self.start_token, jnp.int32)
        # beam 0 live, others -inf so the first top-k doesn't pick clones
        log_probs = jnp.tile(
            jnp.array([[0.0] + [-1e9] * (self.beam_size - 1)], jnp.float32),
            (B, 1))
        finished = jnp.zeros((B, self.beam_size), bool)
        return ids, {"cell": states, "log_probs": log_probs,
                     "finished": finished}

    def step(self, time, inputs, states, **kwargs):
        B = states["log_probs"].shape[0]
        K, V = self.beam_size, None
        emb = self.embedding_fn(Tensor(self._merge(inputs))) \
            if self.embedding_fn is not None else Tensor(self._merge(inputs))
        cell_out, new_cell = self.cell(emb, jax.tree_util.tree_map(
            Tensor, states["cell"]))
        logits = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        logits = getattr(logits, "_data", logits)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = self._split(logp, B)                              # (B, K, V)
        # finished beams only extend with end_token at no cost
        fin = states["finished"][:, :, None]
        onehot_end = jax.nn.one_hot(self.end_token, V, dtype=jnp.float32)
        masked = jnp.where(fin, jnp.log(onehot_end + 1e-38)[None, None, :],
                           logp)
        total = states["log_probs"][:, :, None] + masked          # (B, K, V)
        flat = total.reshape(B, K * V)
        top_val, top_idx = jax.lax.top_k(flat, K)                 # (B, K)
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)

        binx = jnp.arange(B)[:, None]
        new_states = jax.tree_util.tree_map(
            lambda s: self._merge(self._split(getattr(s, "_data", s), B)
                                  [binx, parent]),
            new_cell)
        finished = states["finished"][binx, parent] | (token == self.end_token)
        return token, {"cell": jax.tree_util.tree_map(
            lambda s: getattr(s, "_data", s), new_states),
            "log_probs": top_val, "finished": finished}, parent

    def finalize(self, step_ids, step_parents, final_states):
        ids = jnp.stack(step_ids)            # (T, B, K)
        parents = jnp.stack(step_parents)
        full = gather_tree(Tensor(ids), Tensor(parents))
        return full, final_states


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every beam finishes or max_step_num
    (reference decode.py dynamic_decode).

    Returns (ids, final_log_probs) with ids (B, K, T) (time-major when
    requested), plus per-beam lengths when ``return_length``.
    """
    ids, states = decoder.initialize(inits)
    step_ids, step_parents = [], []
    tokens = ids[:, :]  # (B, K) current input tokens
    for t in range(max_step_num):
        tokens, states, parents = decoder.step(t, tokens, states, **kwargs)
        step_ids.append(tokens)
        step_parents.append(parents)
        if bool(np.asarray(states["finished"]).all()):
            break
    full, final_states = decoder.finalize(step_ids, step_parents, states)
    seq = getattr(full, "_data", full)                 # (T, B, K)
    if not output_time_major:
        seq = jnp.transpose(seq, (1, 2, 0))            # (B, K, T)
    out = Tensor(seq)
    if return_length:
        # length = first end_token position + 1 (or T)
        tdim = 0 if output_time_major else -1
        is_end = (seq == decoder.end_token)
        T = seq.shape[tdim]
        pos = jnp.argmax(is_end.astype(jnp.int32), axis=tdim)
        any_end = jnp.any(is_end, axis=tdim)
        length = jnp.where(any_end, pos + 1, T)
        return out, Tensor(final_states["log_probs"]), Tensor(length)
    return out, Tensor(final_states["log_probs"])
