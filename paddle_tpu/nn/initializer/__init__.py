"""Parameter initializers (reference: python/paddle/nn/initializer/,
fluid/initializer.py).  Each initializer is a callable ``(shape, dtype) -> jax.Array``
drawing from the framework RNG streams."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import rng
from ...core.dtype import convert_dtype


def _fan(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(int(s) for s in shape), self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        sample_dt = jnp.float32 if dt in (jnp.bfloat16, jnp.float16) else dt
        out = self.mean + self.std * jax.random.normal(rng.next_key(),
                                                       tuple(int(s) for s in shape), sample_dt)
        return out.astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        sample_dt = jnp.float32 if dt in (jnp.bfloat16, jnp.float16) else dt
        out = self.mean + self.std * jax.random.truncated_normal(
            rng.next_key(), -2.0, 2.0, tuple(int(s) for s in shape), sample_dt)
        return out.astype(dt)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        sample_dt = jnp.float32 if dt in (jnp.bfloat16, jnp.float16) else dt
        out = jax.random.uniform(rng.next_key(), tuple(int(s) for s in shape), sample_dt,
                                 self.low, self.high)
        return out.astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity == "leaky_relu" \
            else math.sqrt(2.0)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity == "leaky_relu" \
            else math.sqrt(2.0)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = np.asarray(getattr(self.value, "_data", self.value))
        return jnp.asarray(arr, convert_dtype(dtype)).reshape(tuple(int(s) for s in shape))


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        shape = tuple(int(s) for s in shape)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(rng.next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                out[(g * (oc // self.groups) + i, i) + centers] = 1.0
        return jnp.asarray(out, convert_dtype(dtype))


def calculate_gain(nonlinearity: str, param=None) -> float:
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


# fluid-era aliases (reference: fluid/initializer.py)
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv (reference:
    fluid/initializer.py BilinearInitializer — nn/initializer/__init__.py
    exports it as Bilinear).  Weight shape (C_out, C_in, kH, kW)."""

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError(f"Bilinear expects a 4-D conv weight, got {shape}")
        # every (out, in) channel pair gets the bilinear kernel, exactly as
        # the reference writes weight[i] = filt for all flat indices; like the
        # reference, f derives from shape[3] and serves both axes
        f = int(np.ceil(shape[3] / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:shape[2], :shape[3]]
        filt = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        out = np.broadcast_to(filt.astype(np.float32), shape)
        return jnp.asarray(np.ascontiguousarray(out), convert_dtype(dtype))


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """Set process-wide default initializers (reference: fluid/initializer.py
    set_global_initializer).  Layers consult this when no explicit
    weight_attr/bias_attr initializer is given; pass None to reset."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init)


def get_global_initializer():
    return _global_initializer
