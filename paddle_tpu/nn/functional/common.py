"""Common functionals: linear, dropout, embedding, interpolate, one_hot, …
(reference: python/paddle/nn/functional/common.py, input.py, vision.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import rng
from ...core.dtype import convert_dtype
from ...core.tensor import Tensor, apply


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's (in, out) weight layout."""
    def f(a, w, b):
        from ...amp import cast_if_amp
        a, w = cast_if_amp(a, w)
        out = jnp.matmul(a, w)
        if b is not None:
            out = out + b.astype(out.dtype)
        return out
    return apply(f, x, weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x)
        return x if isinstance(x, Tensor) else Tensor(x)
    key = rng.next_key()

    def f(a, k):
        if axis is None:
            mask_shape = a.shape
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            mask_shape = tuple(a.shape[i] if i in axes else 1 for i in range(a.ndim))
        keep = jax.random.bernoulli(k, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply(f, x, Tensor(key))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = rng.next_key()

    def f(a, k):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(k, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef
    return apply(f, x, Tensor(key))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (i != pid)[..., None]
            out = out * mask.astype(out.dtype)
        return out
    return apply(f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, pd):
        k = l.shape[-1]
        if pd is not None:
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return apply(f, label, prior_dist)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    def f(a):
        is_nchw = data_format[1] == "C"
        spatial_dims = list(range(2, a.ndim)) if is_nchw else list(range(1, a.ndim - 1))
        in_sizes = [a.shape[d] for d in spatial_dims]
        if size is not None:
            out_sizes = [int(getattr(s, "item", lambda: s)()) if not isinstance(s, int) else s
                         for s in (size if isinstance(size, (list, tuple)) else
                                   np.asarray(getattr(size, "_data", size)).tolist())]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(in_sizes)
            out_sizes = [int(i * s) for i, s in zip(in_sizes, sf)]
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                 "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode.lower()]
        new_shape = list(a.shape)
        for d, o in zip(spatial_dims, out_sizes):
            new_shape[d] = o
        if jmode == "nearest":
            # paddle nearest: floor(i * scale)
            out = a
            for d, o in zip(spatial_dims, out_sizes):
                idx = jnp.floor(jnp.arange(o) * (a.shape[d] / o)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=d)
            return out
        if align_corners:
            out = a
            for d, o in zip(spatial_dims, out_sizes):
                in_sz = out.shape[d]
                pos = jnp.linspace(0.0, in_sz - 1.0, o)
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, in_sz - 1)
                w = (pos - lo).astype(a.dtype)
                g_lo = jnp.take(out, lo, axis=d)
                g_hi = jnp.take(out, hi, axis=d)
                shape = [1] * out.ndim
                shape[d] = o
                w = w.reshape(shape)
                out = g_lo * (1 - w) + g_hi * w
            return out
        return jax.image.resize(a, tuple(new_shape), method=jmode)
    return apply(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            a = a.reshape(N, C // (r * r), r, r, H, W)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = a.shape
        a = a.reshape(N, H, W, C // (r * r), r, r)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(N, H * r, W * r, C // (r * r))
    return apply(f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            a = a.reshape(N, C, H // r, r, W // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = a.shape
        a = a.reshape(N, H // r, r, W // r, r, C)
        a = a.transpose(0, 2, 4, 5, 1, 3)
        return a.reshape(N, H // r, W // r, C * r * r)
    return apply(f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            N, C = a.shape[:2]
            rest = a.shape[2:]
            a = a.reshape((N, groups, C // groups) + rest)
            a = jnp.swapaxes(a, 1, 2)
            return a.reshape((N, C) + rest)
        N = a.shape[0]
        C = a.shape[-1]
        mid = a.shape[1:-1]
        a = a.reshape((N,) + mid + (groups, C // groups))
        a = jnp.swapaxes(a, -1, -2)
        return a.reshape((N,) + mid + (C,))
    return apply(f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply(f, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi is not None:
            out = out + bi
        return out
    return apply(f, x1, x2, weight, bias)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def f(th):
        N, H, W = out_shape[0], out_shape[2], out_shape[3]
        if align_corners:
            xs = jnp.linspace(-1, 1, W)
            ys = jnp.linspace(-1, 1, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # H,W,3
        return jnp.einsum("nij,hwj->nhwi", th, base)
    return apply(f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True,
                name=None):
    def f(a, g):
        N, C, H, W = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def sample(img, yy, xx):
            yy_c = jnp.clip(yy, 0, H - 1)
            xx_c = jnp.clip(xx, 0, W - 1)
            v = img[:, :, yy_c.astype(jnp.int32), xx_c.astype(jnp.int32)]
            # gather per batch: use vmap
            return v
        bidx = jnp.arange(N)[:, None, None]
        if mode == "nearest":
            yy = jnp.round(fy).astype(jnp.int32)
            xx = jnp.round(fx).astype(jnp.int32)
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yy = jnp.clip(yy, 0, H - 1)
            xx = jnp.clip(xx, 0, W - 1)
            out = a[bidx, :, yy, xx]  # N,Hg,Wg,C
            out = jnp.where(valid[..., None], out, 0.0)
            return jnp.moveaxis(out, -1, 1)
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = fx - x0
        wy = fy - y0
        out = 0
        for yi, wyi in ((y0, 1 - wy), (y1, wy)):
            for xi, wxi in ((x0, 1 - wx), (x1, wx)):
                valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                yc = jnp.clip(yi, 0, H - 1)
                xc = jnp.clip(xi, 0, W - 1)
                v = a[bidx, :, yc, xc]  # N,Hg,Wg,C
                w = (wyi * wxi)[..., None]
                if padding_mode == "zeros":
                    v = jnp.where(valid[..., None], v, 0.0)
                out = out + v * w.astype(a.dtype)
        return jnp.moveaxis(out, -1, 1)
    return apply(f, x, grid)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import unfold as _unfold
    return _unfold(x, kernel_sizes, strides, paddings, dilations)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import fold as _fold
    return _fold(x, output_sizes, kernel_sizes, strides, paddings, dilations)
