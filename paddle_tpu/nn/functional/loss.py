"""Loss functionals (reference: python/paddle/nn/functional/loss.py,
operators/cross_entropy_op.*, softmax_with_cross_entropy_op.*)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def f(logits, lab, w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            tgt = lab
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(loss, reduction)
        lab_idx = lab
        if lab_idx.ndim == logits.ndim:  # trailing 1 dim
            lab_idx = jnp.squeeze(lab_idx, axis)
        lab_idx = lab_idx.astype(jnp.int32)
        valid = lab_idx != ignore_index
        safe = jnp.where(valid, lab_idx, 0)
        picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32),
                                     axis=axis).squeeze(axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = -picked
        if w is not None:
            loss = loss * jnp.take(w, safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if w is not None:
                denom = jnp.sum(jnp.where(valid, jnp.take(w, safe), 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(f, input, label, weight)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, reduction="none", soft_label=soft_label,
                         ignore_index=ignore_index, axis=axis)
    loss = apply(lambda l: l[..., None] if l.ndim >= 0 else l, loss) \
        if not soft_label else loss
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(logp, lab, w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=1).squeeze(1)
        loss = -picked
        if w is not None:
            wt = jnp.take(w, safe)
            loss = loss * wt
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, jnp.take(w, safe) if w is not None else 1.0, 0.0))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(f, input, label, weight)


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply(f, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return apply(f, input, label, weight)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, w, pw):
        max_val = jnp.maximum(-z, 0.0)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = (1 - y) * z + jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return apply(f, logit, label, weight, pos_weight)


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
                 input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(margin - a, 0.0)),
                                      reduction), input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)
    return apply(f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(f, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
                 input, label)


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            a_t = alpha * y + (1 - alpha) * (1 - y)
            loss = a_t * loss
        if nrm is not None:
            loss = loss / nrm
        return _reduce(loss, reduction)
    return apply(f, logit, label, normalizer)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over time)."""
    def f(lp, lab, in_len, lab_len):
        # lp: (T, B, C) log-softmax already? paddle expects raw logits of (T,B,C)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        NEG = jnp.array(-1e30, lp.dtype)
        # extended labels: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        # alpha init
        alpha0 = jnp.full((B, S), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0,
                                               lp[0, jnp.arange(B), ext[:, 1]], NEG))

        same = jnp.concatenate([jnp.zeros((B, 2), bool),
                                ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            a3 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            a3 = jnp.where(same | (jnp.arange(S)[None, :] % 2 == 0), NEG, a3)
            m = jnp.maximum(jnp.maximum(a1, a2), a3)
            m_safe = jnp.where(m == NEG, 0.0, m)
            s = jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe) + jnp.exp(a3 - m_safe)
            new = jnp.where(m == NEG, NEG, m_safe + jnp.log(s))
            emit = lp_t[jnp.arange(B)[:, None], ext]
            return new + emit, None

        def scan_step(carry, inp):
            alpha, t = carry
            lp_t = inp
            new_alpha, _ = step(alpha, lp_t)
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return (new_alpha, t + 1), None

        (alphaT, _), _ = jax.lax.scan(scan_step, (alpha0, jnp.ones((), jnp.int32)),
                                      lp[1:])
        end1 = alphaT[jnp.arange(B), 2 * lab_len]
        end2 = alphaT[jnp.arange(B), jnp.maximum(2 * lab_len - 1, 0)]
        m = jnp.maximum(end1, end2)
        m_safe = jnp.where(m == NEG, 0.0, m)
        ll = m_safe + jnp.log(jnp.exp(end1 - m_safe) + jnp.exp(end2 - m_safe))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(f, log_probs, labels, input_lengths, label_lengths)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, y):
        B = a.shape[0]
        sim = a @ p.T
        y = y.reshape(-1, 1)
        tgt = (y == y.T).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply(f, anchor, positive, labels)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2·|A∩B| / (|A|+|B|+eps), mean over batch (reference dice_loss:
    epsilon in the denominator only, so an empty mask scores loss 1)."""
    def f(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = 2 * jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - inter / (union + epsilon))
    return apply(f, input, label)
