"""Activation functionals (reference: python/paddle/nn/functional/activation.py,
operators/activation_op.*)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def relu(x, name=None):
    return apply(jax.nn.relu, x)


def relu_(x, name=None):
    out = relu(x)
    x._adopt(out)
    return x


def relu6(x, name=None):
    return apply(lambda a: jnp.clip(a, 0.0, 6.0), x)


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x)


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)), x)


def tanhshrink(x, name=None):
    return apply(lambda a: a - jnp.tanh(a), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply(f, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    from ...core import rng
    from ...core.tensor import Tensor
    if training:
        key = rng.next_key()
        return apply(lambda a, k: jnp.where(
            a >= 0, a, a * jax.random.uniform(k, a.shape, a.dtype, lower, upper)), x, Tensor(key))
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, a * mid), x)


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x)


def maxout(x, groups, axis=1, name=None):
    """Max over ``groups`` consecutive channels: channel block i is
    [i*groups, (i+1)*groups) (reference maxout_op semantics,
    test_maxout_op.py:29 — (C//groups, groups) with max over the last)."""
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        newshape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(newshape), axis=ax + 1)
    return apply(f, x)


def silu(x, name=None):
    return apply(jax.nn.silu, x)


swish = silu


def mish(x, name=None):
    return apply(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda a: jnp.where(a * beta > threshold, a,
                                     jax.nn.softplus(a * beta) / beta), x)


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x)


def tanh(x, name=None):
    return apply(jnp.tanh, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, value), x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    def f(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply(f, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._adopt(out)
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    def f(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply(f, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import rng
    from ...core.tensor import Tensor
    key = rng.next_key()

    def f(a, k):
        g = jax.random.gumbel(k, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) \
                if hasattr(jnp, "put_along_axis") else \
                jnp.zeros_like(y).at[...].set(jnp.where(
                    jax.lax.broadcasted_iota(jnp.int32, y.shape, axis % y.ndim) == idx, 1.0, 0.0))
            return y_hard + jax.lax.stop_gradient(-y) + y
        return y
    return apply(f, x, Tensor(key))


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), x)
