"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py,
operators/pool_op.*).  All lower to ``jax.lax.reduce_window``."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dtype import convert_dtype

from ...core.tensor import apply
from .conv import _padding, _tuplize


def _window(n, data_format, k, s):
    if data_format[1] == "C":  # NCHW-family
        win = (1, 1) + k
        stride = (1, 1) + s
        spatial = list(range(2, 2 + n))
    else:
        win = (1,) + k + (1,)
        stride = (1,) + s + (1,)
        spatial = list(range(1, 1 + n))
    return win, stride, spatial


def _full_pad(pad, n, ndim, spatial):
    full = [(0, 0)] * ndim
    if isinstance(pad, str):
        return pad
    for d, p in zip(spatial, pad):
        full[d] = p
    return full


def _resolve_pads(a_shape, win, st, pad, n, spatial, k, s, ceil_mode, ndim):
    """Resolve paddle padding spec + ceil_mode into explicit lax pads."""
    pd = _full_pad(pad, n, ndim, spatial)
    if isinstance(pd, str):
        return jax.lax.padtype_to_pads(a_shape, win, st, pd)
    pd_resolved = list(pd)
    if ceil_mode:
        for i, d in enumerate(spatial):
            size = a_shape[d] + pd_resolved[d][0] + pd_resolved[d][1]
            rem = (size - k[i]) % s[i]
            if rem != 0:
                lo, hi = pd_resolved[d]
                pd_resolved[d] = (lo, hi + (s[i] - rem))
    return pd_resolved


def _pool(x, kernel, stride, padding, n, data_format, reducer, init, ceil_mode=False,
          count_include_pad=True, average=False, exclusive=True):
    k = _tuplize(kernel, n)
    s = _tuplize(stride if stride is not None else kernel, n)
    pad = _padding(padding, n, data_format)

    def f(a):
        win, st, spatial = _window(n, data_format, k, s)
        pd_resolved = _resolve_pads(a.shape, win, st, pad, n, spatial, k, s, ceil_mode,
                                    a.ndim)
        if not average:
            iv = init(a.dtype)
            iv = jnp.asarray(iv, a.dtype) if not isinstance(iv, float) else iv
            return jax.lax.reduce_window(a, iv, reducer, win, st, pd_resolved)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, win, st, pd_resolved)
        if exclusive:
            ones = jnp.ones(tuple(a.shape[d] for d in spatial), a.dtype)
            ones = ones.reshape([a.shape[d] if d in spatial else 1 for d in range(a.ndim)])
            counts = jax.lax.reduce_window(
                jnp.broadcast_to(ones, a.shape) * 0 + 1, jnp.zeros((), a.dtype),
                jax.lax.add, win, st, pd_resolved)
            return summed / counts
        denom = np.prod(k)
        return summed / denom
    return apply(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, data_format,
                jax.lax.max, lambda dt: (-float("inf") if jnp.issubdtype(dt, jnp.floating)
                                       else jnp.iinfo(dt).min),
                ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_indices(x, kernel_size, stride, padding, 1, data_format, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format,
                jax.lax.max, lambda dt: (-float("inf") if jnp.issubdtype(dt, jnp.floating)
                                       else jnp.iinfo(dt).min),
                ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_indices(x, kernel_size, stride, padding, 2, data_format, ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format,
                jax.lax.max, lambda dt: (-float("inf") if jnp.issubdtype(dt, jnp.floating)
                                       else jnp.iinfo(dt).min),
                ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_indices(x, kernel_size, stride, padding, 3, data_format, ceil_mode)
    return out


def _pool_indices(x, kernel, stride, padding, n, data_format, ceil_mode):
    """Argmax indices within flattened spatial dims (paddle return_mask contract)."""
    def f(a):
        spatial_shape = a.shape[2:] if data_format[1] == "C" else a.shape[1:-1]
        numel = int(np.prod(spatial_shape))
        iota = jnp.arange(numel, dtype=jnp.float32).reshape(spatial_shape)
        if data_format[1] == "C":
            iota_b = jnp.broadcast_to(iota, a.shape)
        else:
            iota_b = jnp.broadcast_to(iota.reshape(spatial_shape + (1,)), a.shape)
        k = _tuplize(kernel, n)
        s = _tuplize(stride if stride is not None else kernel, n)
        pad = _padding(padding, n, data_format)
        win, st, spatial = _window(n, data_format, k, s)
        pd = _resolve_pads(a.shape, win, st, pad, n, spatial, k, s, ceil_mode, a.ndim)

        def red(acc, cur):
            av, ai = acc
            cv, ci = cur
            take = cv > av
            return jnp.where(take, cv, av), jnp.where(take, ci, ai)
        vals, idxs = jax.lax.reduce_window(
            (a, iota_b), (jnp.array(-jnp.inf, a.dtype), jnp.array(-1.0, jnp.float32)),
            red, win, st, pd)
        return idxs.astype(convert_dtype("int64"))
    return apply(f, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, average=True, exclusive=exclusive)


def _adaptive_axes(in_size, out_size):
    # start/end indices per output cell
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, data_format, op):
    def f(a):
        spatial = list(range(2, 2 + n)) if data_format[1] == "C" else list(range(1, 1 + n))
        outs = _tuplize(output_size, n)
        out = a
        for dim_i, d in enumerate(spatial):
            o = outs[dim_i]
            if o is None:
                continue
            in_size = out.shape[d]
            if in_size % o == 0:
                # uniform: reshape-reduce (fast path, XLA-friendly)
                factor = in_size // o
                new_shape = out.shape[:d] + (o, factor) + out.shape[d + 1:]
                out = getattr(jnp, op)(out.reshape(new_shape), axis=d + 1)
            else:
                starts, ends = _adaptive_axes(in_size, o)
                slices = [getattr(jnp, op)(jax.lax.slice_in_dim(out, s, e, axis=d), axis=d)
                          for s, e in zip(starts, ends)]
                out = jnp.stack(slices, axis=d)
        return out
    return apply(f, x)


def _adaptive_max_mask(x, output_size, n, data_format):
    """Indices (flattened within input spatial dims) of each adaptive-max cell."""
    def f(a):
        spatial = list(range(2, 2 + n)) if data_format[1] == "C" else list(range(1, 1 + n))
        outs = _tuplize(output_size, n)
        in_sizes = [a.shape[d] for d in spatial]
        flat_sp = int(np.prod(in_sizes))
        iota = jnp.arange(flat_sp, dtype=jnp.float32).reshape(in_sizes)
        if data_format[1] == "C":
            iota_b = jnp.broadcast_to(iota, a.shape)
        else:
            iota_b = jnp.broadcast_to(iota.reshape(tuple(in_sizes) + (1,)), a.shape)
        vals, idxs = a, iota_b
        for dim_i, d in enumerate(spatial):
            o = outs[dim_i]
            in_size = vals.shape[d]
            starts, ends = _adaptive_axes(in_size, o)
            v_sl, i_sl = [], []
            for s, e in zip(starts, ends):
                vv = jax.lax.slice_in_dim(vals, s, e, axis=d)
                ii = jax.lax.slice_in_dim(idxs, s, e, axis=d)
                am = jnp.argmax(vv, axis=d, keepdims=True)
                v_sl.append(jnp.take_along_axis(vv, am, axis=d).squeeze(d))
                i_sl.append(jnp.take_along_axis(ii, am, axis=d).squeeze(d))
            vals = jnp.stack(v_sl, axis=d)
            idxs = jnp.stack(i_sl, axis=d)
        return idxs.astype(convert_dtype("int64"))
    return apply(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", "mean")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "mean")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "mean")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "NCL", "max")
    if return_mask:
        return out, _adaptive_max_mask(x, output_size, 1, "NCL")
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "NCHW", "max")
    if return_mask:
        return out, _adaptive_max_mask(x, output_size, 2, "NCHW")
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "NCDHW", "max")
    if return_mask:
        return out, _adaptive_max_mask(x, output_size, 3, "NCDHW")
    return out
