"""Convolution functionals (reference: python/paddle/nn/functional/conv.py,
operators/conv_op.*).

TPU-first: all convs lower to ``jax.lax.conv_general_dilated`` so XLA tiles
them onto the MXU; NCHW (paddle default) and NHWC are both supported with the
dimension-numbers mechanism rather than explicit transposes.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import apply


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _padding(padding, n, data_format):
    """Normalize paddle padding spec → lax [(lo,hi)]*n or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        # [[lo,hi],...] possibly including batch/channel dims
        if len(padding) == n + 2:
            spatial = padding[2:] if data_format[1] == "C" else padding[1:-1]
            return [tuple(p) for p in spatial]
        return [tuple(p) for p in padding]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _dimnums(n, data_format):
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs = "NC" + "DHW"[3 - n:]
        out = lhs
    else:
        lhs = "N" + "DHW"[3 - n:] + "C"
        out = lhs
    rhs = "OI" + "DHW"[3 - n:]
    return jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2), (lhs, rhs, out))


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    pad = _padding(padding, n, data_format)
    dn = _dimnums(n, data_format)

    def f(a, w, b):
        from ...amp import cast_if_amp
        a, w = cast_if_amp(a, w)
        if a.dtype != w.dtype and jnp.issubdtype(a.dtype, jnp.floating) \
                and jnp.issubdtype(w.dtype, jnp.floating):
            # fp32-params / low-precision-compute convention: conv runs in the
            # narrower dtype (bf16 activations × fp32 master weights → bf16
            # MXU conv, matching the transformer stack's weight.astype(dt)).
            # Only applies when the narrower side is a 2-byte compute dtype;
            # other float mismatches promote (never silently lose precision —
            # the reference errors on dtype mismatch, conv_op.cc).
            sizes = (jnp.dtype(a.dtype).itemsize, jnp.dtype(w.dtype).itemsize)
            if min(sizes) == 2 and sizes[0] != sizes[1]:
                dt = a.dtype if sizes[0] < sizes[1] else w.dtype
            else:  # incl. fp16 x bf16: promote, never cast across formats
                dt = jnp.promote_types(a.dtype, w.dtype)
            a, w = a.astype(dt), w.astype(dt)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if b is not None:
            shape = [1] * out.ndim
            shape[1 if data_format[1] == "C" else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out
    return apply(f, x, weight, bias)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                    data_format, n, output_size=None):
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n)
    pad = _padding(padding, n, data_format)
    dn = _dimnums(n, data_format)

    def f(a, w, b):
        # paddle stores transpose-conv weight as (in, out/groups, *k)
        # lax.conv_transpose wants IO...-style; use gradient-based formulation:
        # conv_transpose = conv_general_dilated with lhs_dilation=stride.
        if isinstance(pad, str):
            pd = pad
            lax_pad = pad
            k = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n)]
            if pd == "SAME":
                lax_pad = [((ki - 1) // 2, ki - 1 - (ki - 1) // 2) for ki in k]
            else:
                lax_pad = [(ki - 1, ki - 1) for ki in k]
            base = [(ki - 1, ki - 1) for ki in k]
            eff = lax_pad
        else:
            k = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n)]
            eff = [(ki - 1 - lo, ki - 1 - hi + op)
                   for (lo, hi), ki, op in zip(pad, k, opad)]
        # weight (in, out/groups, *k) → flip spatial, swap to (out, in/groups, *k)
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups == 1:
            wt = jnp.swapaxes(wt, 0, 1)
        else:
            ci, cog = w.shape[0], w.shape[1]
            wt = wt.reshape((groups, ci // groups, cog) + w.shape[2:])
            wt = jnp.swapaxes(wt, 1, 2)
            wt = wt.reshape((groups * cog, ci // groups) + w.shape[2:])
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1,) * n, padding=eff, lhs_dilation=stride,
            rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups)
        if b is not None:
            shape = [1] * out.ndim
            shape[1 if data_format[1] == "C" else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    out = apply(f, x, weight, bias)
    if output_size is not None:
        # crop/pad to requested size if integral mismatch
        pass
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 3, output_size)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/math/im2col.*)."""
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)
    p = _padding(paddings, 2, "NCHW")

    def f(a):
        N, C, H, W = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), p[0], p[1]))
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding=[(0, 0), (0, 0)], rhs_dilation=d,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                a.shape, (1, 1) + k, ("NCHW", "OIHW", "NCHW")))
        # patches: (N, C*kh*kw, OH, OW)
        return patches.reshape(N, patches.shape[1], -1)
    return apply(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)
    p = _padding(paddings, 2, "NCHW")
    OH, OW = _tuplize(output_sizes, 2)

    def f(a):
        N, CKK, L = a.shape
        C = CKK // (k[0] * k[1])
        Hp, Wp = OH + p[0][0] + p[0][1], OW + p[1][0] + p[1][1]
        oh = (Hp - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (Wp - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a = a.reshape(N, C, k[0], k[1], oh, ow)
        out = jnp.zeros((N, C, Hp, Wp), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0], wj:wj + ow * s[1]:s[1]].add(
                    a[:, :, i, j])
        return out[:, :, p[0][0]:Hp - p[0][1], p[1][0]:Wp - p[1][1]]
    return apply(f, x)
