"""Normalization functionals (reference: python/paddle/nn/functional/norm.py,
operators/batch_norm_op.*, layer_norm_op.*)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    """Batch norm.  In training mode the running stats tensors are updated
    in place (no gradient flows through them), matching the reference's
    batch_norm op semantics (momentum convention: new = m*old + (1-m)*batch).
    """
    ch_axis = 1 if data_format[1] == "C" else -1
    use_batch_stats = training and not (use_global_stats is True)

    def stats_fn(a):
        axes = tuple(i for i in range(a.ndim) if i != (ch_axis % a.ndim))
        m = jnp.mean(a, axis=axes)
        v = jnp.var(a, axis=axes)
        return m, v

    if use_batch_stats:
        # compute batch stats (differentiable), update running stats (stopped)
        bm, bv = apply(stats_fn, x)
        if running_mean is not None:
            # reference batch_norm_op.cc:416 uses the *biased* batch variance
            # in the running-stat update (no Bessel correction)
            new_mean = momentum * running_mean._data + (1 - momentum) * jax.lax.stop_gradient(
                getattr(bm, "_data", bm))
            new_var = momentum * running_var._data + (1 - momentum) * jax.lax.stop_gradient(
                getattr(bv, "_data", bv))
            running_mean._data = new_mean.astype(running_mean._data.dtype)
            running_var._data = new_var.astype(running_var._data.dtype)
        mean, var = bm, bv
    else:
        mean, var = running_mean, running_var

    def f(a, m, v, w, b):
        shape = [1] * a.ndim
        shape[ch_axis % a.ndim] = a.shape[ch_axis % a.ndim]
        m = m.reshape(shape)
        v = v.reshape(shape)
        inv = jax.lax.rsqrt(v + epsilon)
        out = (a - m) * inv
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out
    return apply(f, x, mean, var, weight, bias)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndim = len(list(normalized_shape))

    def f(a, w, b):
        from ...amp import blacklist_cast
        in_dtype = a.dtype
        (a,) = blacklist_cast(a)
        axes = tuple(range(a.ndim - ndim, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        if w is not None:
            out = out * w.astype(out.dtype)
        if b is not None:
            out = out + b.astype(out.dtype)
        return out.astype(in_dtype)
    return apply(f, x, weight, bias)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    def f(a, w, b):
        if data_format[1] == "C":
            axes = tuple(range(2, a.ndim))
            ch_axis = 1
        else:
            axes = tuple(range(1, a.ndim - 1))
            ch_axis = a.ndim - 1
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        if w is not None:
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = out + b.reshape(shape)
        return out
    return apply(f, x, weight, bias)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW",
               name=None):
    def f(a, w, b):
        if data_format == "NCHW" or data_format[1] == "C":
            N, C = a.shape[0], a.shape[1]
            rest = a.shape[2:]
            g = a.reshape((N, num_groups, C // num_groups) + rest)
            axes = tuple(range(2, g.ndim))
            m = jnp.mean(g, axis=axes, keepdims=True)
            v = jnp.var(g, axis=axes, keepdims=True)
            g = (g - m) * jax.lax.rsqrt(v + epsilon)
            out = g.reshape(a.shape)
            shape = [1] * a.ndim
            shape[1] = C
        else:
            N, C = a.shape[0], a.shape[-1]
            spatial = a.shape[1:-1]
            g = a.reshape((N,) + spatial + (num_groups, C // num_groups))
            axes = tuple(range(1, a.ndim - 1)) + (a.ndim,)
            m = jnp.mean(g, axis=axes, keepdims=True)
            v = jnp.var(g, axis=axes, keepdims=True)
            g = (g - m) * jax.lax.rsqrt(v + epsilon)
            out = g.reshape(a.shape)
            shape = [1] * a.ndim
            shape[-1] = C
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out
    return apply(f, x, weight, bias)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    def f(a):
        # 2.x semantics (reference nn/functional/norm.py:502-538): window
        # MEAN of x^2 (pad size//2 low, (size-1)//2 high, then avg_pool),
        # denom = (k + alpha*mean)^beta — torch-compatible, NOT the legacy
        # fluid lrn op's alpha*sum
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        sq = jnp.square(a)
        sq_m = jnp.moveaxis(sq, ch_axis, -1)
        pad_lo = size // 2
        pad_hi = (size - 1) // 2
        padded = jnp.pad(sq_m, [(0, 0)] * (sq_m.ndim - 1) + [(pad_lo, pad_hi)])
        win = sum(padded[..., i:i + sq_m.shape[-1]] for i in range(size)) / size
        win = jnp.moveaxis(win, -1, ch_axis)
        return a / jnp.power(k + alpha * win, beta)
    return apply(f, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(n, epsilon)
    return apply(f, x)
