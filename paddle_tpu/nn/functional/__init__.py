"""``paddle_tpu.nn.functional`` (reference: python/paddle/nn/functional/)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from .activation import (celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid,  # noqa: F401
                         hardswish, hardtanh, leaky_relu, log_sigmoid, log_softmax,
                         maxout, mish, prelu, relu, relu6, relu_, rrelu, selu, sigmoid,
                         silu, softmax, softmax_, softplus, softshrink, softsign, swish,
                         tanh, tanhshrink, thresholded_relu)
from .common import (affine_grid, alpha_dropout, bilinear, channel_shuffle,  # noqa: F401
                     cosine_similarity, dropout, dropout2d, dropout3d, embedding, fold,
                     grid_sample, interpolate, label_smooth, linear, one_hot, pad,
                     pixel_shuffle, pixel_unshuffle, unfold, upsample)
from .conv import (conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,  # noqa: F401
                   conv3d_transpose)
from .loss import (binary_cross_entropy, binary_cross_entropy_with_logits,  # noqa: F401
                   cosine_embedding_loss, cross_entropy, ctc_loss, dice_loss,
                   hinge_embedding_loss, kl_div, l1_loss, log_loss, margin_ranking_loss,
                   mse_loss, nll_loss, npair_loss, sigmoid_focal_loss, smooth_l1_loss,
                   softmax_with_cross_entropy, square_error_cost, triplet_margin_loss)
from .norm import (batch_norm, group_norm, instance_norm, layer_norm,  # noqa: F401
                   local_response_norm, normalize)
from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,  # noqa: F401
                      adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
                      avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d,
                      max_pool3d)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtype import convert_dtype

    def f(lens):
        m = maxlen if maxlen is not None else int(jnp.max(lens))
        iota = jnp.arange(m)
        return (iota[None, :] < lens[..., None]).astype(convert_dtype(dtype))
    return apply(f, x)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def f(a):
        n = a.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        # move last two dims into requested positions
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out
    return apply(f, input)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None, data_format="NCHW"):
    def f(a):
        NT, C, H, W = a.shape
        N = NT // seg_num
        a = a.reshape(N, seg_num, C, H, W)
        fold_c = int(C * shift_ratio)
        out = jnp.zeros_like(a)
        out = out.at[:, 1:, :fold_c].set(a[:, :-1, :fold_c])
        out = out.at[:, :-1, fold_c:2 * fold_c].set(a[:, 1:, fold_c:2 * fold_c])
        out = out.at[:, :, 2 * fold_c:].set(a[:, :, 2 * fold_c:])
        return out.reshape(NT, C, H, W)
    return apply(f, x)


def npu_identity(x, format=-1):
    return x


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (reference: nn/functional/sparse_attention.py).

    Dense fallback honoring the CSR mask; the Pallas block-sparse kernel lives
    in paddle_tpu.ops.flash_attention for the performant path.
    """
    def f(q, k, v, offs, cols):
        B, H, L, D = q.shape
        scale = 1.0 / jnp.sqrt(D).astype(q.dtype)
        scores = jnp.einsum("bhld,bhmd->bhlm", q, k) * scale
        # build dense mask from CSR
        def one_mask(off, col):
            row_ids = jnp.searchsorted(off, jnp.arange(col.shape[0]), side="right") - 1
            m = jnp.zeros((L, L), bool).at[row_ids, col].set(True)
            return m
        mask = jax.vmap(jax.vmap(one_mask))(offs[..., :], cols[..., :]) \
            if offs.ndim == 3 else one_mask(offs, cols)
        scores = jnp.where(mask, scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhlm,bhmd->bhld", attn, v)
    return apply(f, query, key, value, sparse_csr_offset, sparse_csr_columns)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Flash-attention entry point (BSHD layout like paddle's incubate API)."""
    from ...ops.attention import scaled_dot_product_attention as sdpa
    return sdpa(query, key, value, attn_mask, dropout_p, is_causal, training)

from .extras import (class_center_sample, elu_, gather_tree, hsigmoid_loss,  # noqa: F401,E402
                     margin_cross_entropy, max_unpool2d, tanh_, zeropad2d)
