"""Remaining functional ops for reference nn.functional parity:
max_unpool2d, zeropad2d, inplace activations, hierarchical-sigmoid loss,
margin (ArcFace) cross entropy, class-center sampling, beam-search
gather_tree.

TPU-native notes are per function; everything is static-shape and
jit-safe (class_center_sample fixes the sample count; hsigmoid precomputes
the tree tables host-side per num_classes).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core.tensor import Tensor, apply

__all__ = ["max_unpool2d", "zeropad2d", "elu_", "tanh_", "hsigmoid_loss",
           "margin_cross_entropy", "class_center_sample", "gather_tree"]


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True): scatter each pooled value
    back to its argmax position (indices are flattened INPUT-spatial ids,
    the contract our _pool_indices emits)."""
    if data_format != "NCHW":
        raise ValueError("max_unpool2d supports NCHW only")
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride))
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def f(a, idx):
        B, C, H, W = a.shape
        if output_size is not None:
            Ho, Wo = output_size[-2:]
        else:
            Ho = (H - 1) * st[0] - 2 * pd[0] + ks[0]
            Wo = (W - 1) * st[1] - 2 * pd[1] + ks[1]
        flat = jnp.zeros((B, C, Ho * Wo), a.dtype)
        bi = jnp.arange(B)[:, None, None]
        ci = jnp.arange(C)[None, :, None]
        ids = idx.reshape(B, C, H * W).astype(jnp.int32)
        flat = flat.at[bi, ci, ids].set(a.reshape(B, C, H * W))
        return flat.reshape(B, C, Ho, Wo)

    return apply(f, x, indices)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W (reference nn/functional/common.py zeropad2d);
    padding = [left, right, top, bottom]."""
    l, r, t, b = padding

    def f(a):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (t, b), (l, r)]
        else:
            cfg = [(0, 0), (t, b), (l, r), (0, 0)]
        return jnp.pad(a, cfg)

    return apply(f, x)


def elu_(x, alpha=1.0, name=None):
    from . import elu
    x._adopt(elu(x, alpha))
    return x


def tanh_(x, name=None):
    x._adopt(apply(jnp.tanh, x))
    return x


# ---------------------------------------------------------------------------
# hierarchical sigmoid
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _hsig_tree(num_classes: int):
    """(paths, codes, mask) int arrays (C, depth) for the heap-layout
    complete binary tree the reference's default path uses: internal nodes
    0..C-2, leaf for class c sits at heap id c + C - 1."""
    depth = max(1, math.ceil(math.log2(max(num_classes, 2))))
    paths = np.zeros((num_classes, depth), np.int32)
    codes = np.zeros((num_classes, depth), np.float32)
    mask = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = c + num_classes - 1
        chain = []
        while node > 0:
            parent = (node - 1) // 2
            chain.append((parent, float(node == 2 * parent + 2)))
            node = parent
        chain.reverse()
        for d, (p, bit) in enumerate(chain):
            paths[c, d] = p
            codes[c, d] = bit
            mask[c, d] = 1.0
    return jnp.asarray(paths), jnp.asarray(codes), jnp.asarray(mask)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py
    hsigmoid_loss, operators/hierarchical_sigmoid_op.h).

    Default tree: complete binary heap over num_classes leaves; custom
    trees via (path_table, path_code) exactly like the reference.  weight:
    (num_classes - 1, D); returns (N, 1) loss (sum over the path of BCE
    with the path code).
    """
    def f(x, lbl, w, b, ptab, pcode):
        lbl = lbl.reshape(-1).astype(jnp.int32)
        if ptab is None:
            paths, codes, mask = _hsig_tree(int(num_classes))
            p = paths[lbl]          # (N, depth)
            c = codes[lbl]
            m = mask[lbl]
        else:
            # reference contract: custom tables are PER-SAMPLE (N, depth)
            # rows already gathered by the caller — never re-indexed here
            # (shape-based guessing would misread a batch of size
            # num_classes); entries < 0 pad ragged paths
            if ptab.shape[0] != lbl.shape[0]:
                raise ValueError(
                    f"path_table must have one row per sample "
                    f"({lbl.shape[0]}), got {ptab.shape}")
            p = ptab.astype(jnp.int32)
            c = pcode.astype(jnp.float32)
            m = (p >= 0).astype(jnp.float32)
            p = jnp.maximum(p, 0)
        wn = w[p]                    # (N, depth, D)
        logits = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                            wn.astype(jnp.float32))
        if b is not None:
            logits = logits + b.reshape(-1)[p]
        # BCE against the code bit, masked to the true path length
        losses = m * (jnp.logaddexp(0.0, logits) - c * logits)
        return jnp.sum(losses, axis=1, keepdims=True)

    return apply(f, input, label, weight, bias, path_table, path_code)


# ---------------------------------------------------------------------------
# margin softmax (ArcFace family) + PartialFC sampling
# ---------------------------------------------------------------------------

def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """CosFace/ArcFace margin softmax CE (reference
    nn/functional/margin_cross_entropy; the class-parallel ``group`` path is
    subsumed by GSPMD sharding of the class dim — pass group=None and shard
    the logits instead).

    logits are cosines; the target class logit cosθ becomes
    cos(margin1·θ + margin2) − margin3 before scaling.
    """
    if group is not None:
        raise ValueError(
            "explicit process groups are not used on TPU; shard the class "
            "dim of logits with a NamedSharding and GSPMD handles the rest")

    def f(cos, lbl):
        lbl = lbl.reshape(-1).astype(jnp.int32)
        # keep strictly inside (-1, 1): arccos' blows up at the endpoints and
        # a saturated target cosine would send NaN through backward
        lim = 1.0 - 1e-6
        cosf = jnp.clip(cos.astype(jnp.float32), -lim, lim)
        theta = jnp.arccos(jnp.take_along_axis(cosf, lbl[:, None], axis=1))[:, 0]
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lbl, cos.shape[-1], dtype=jnp.float32)
        adjusted = cosf * (1 - onehot) + target[:, None] * onehot
        z = adjusted * scale
        logp = jax.nn.log_softmax(z, axis=-1)
        loss = -jnp.take_along_axis(logp, lbl[:, None], axis=1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return apply(f, logits, label)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC negative-class sampling (reference
    nn/functional/class_center_sample).  Keeps every positive class plus
    uniformly sampled negatives up to ``num_samples`` (static shape), and
    remaps labels into the sampled index space (-1 style semantics: labels
    keep their position since positives always survive).

    Returns (remapped_label, sampled_class_center) — sampled ids sorted,
    positives first in sorted order like the reference.
    """
    if group is not None:
        raise ValueError("explicit process groups are not used on TPU")
    if num_samples > num_classes:
        raise ValueError(
            f"num_samples ({num_samples}) cannot exceed num_classes "
            f"({num_classes})")
    from ...core import rng

    def f(lbl, key):
        lbl = lbl.reshape(-1).astype(jnp.int32)
        pos = jnp.zeros((num_classes,), bool).at[lbl].set(True)
        n_pos = jnp.sum(pos)
        # rank classes: positives (random order) first, then shuffled
        # negatives; take num_samples — positives always make the cut
        # as long as num_samples >= #positives (reference contract)
        noise = jax.random.uniform(key, (num_classes,))
        rank = jnp.where(pos, noise - 1.0, noise)   # positives sort first
        order = jnp.argsort(rank)
        sampled = jnp.sort(order[:num_samples])
        # remap: position of each label inside `sampled`
        inv = jnp.full((num_classes,), -1, jnp.int32)
        inv = inv.at[sampled].set(jnp.arange(num_samples, dtype=jnp.int32))
        return inv[lbl], sampled.astype(jnp.int32)

    return apply(f, label, Tensor(rng.next_key()))


# ---------------------------------------------------------------------------
# beam search backtrace
# ---------------------------------------------------------------------------

def gather_tree(ids, parents):
    """Reconstruct full beam paths from per-step ids and parent pointers
    (reference nn/functional gather_tree, gather_tree_op.cc).

    ids, parents: (T, B, beam) int.  Walks from the last step backwards —
    a ``lax.scan`` over time, fully on device.
    """
    def f(idv, par):
        idv = idv.astype(jnp.int32)
        par = par.astype(jnp.int32)
        T, B, K = idv.shape
        binx = jnp.arange(B)[:, None]

        def back(beam_at_t, xs):
            ids_t, par_t = xs
            out = ids_t[binx, beam_at_t]            # (B, K)
            prev = par_t[binx, beam_at_t]
            return prev, out

        init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, outs = lax.scan(back, init, (idv[::-1], par[::-1]))
        return outs[::-1]

    return apply(f, ids, parents)
