"""``paddle_tpu.nn`` (reference: python/paddle/nn/)."""

from . import functional, initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer  # noqa: F401
from .utils import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters  # noqa: F401
from . import quant  # noqa: F401,E402
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401,E402
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401,E402
from .utils import spectral_norm  # noqa: F401,E402
