"""``paddle_tpu.nn`` (reference: python/paddle/nn/)."""

from . import functional, initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer  # noqa: F401
from .utils import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters  # noqa: F401
