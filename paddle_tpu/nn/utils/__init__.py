"""nn.utils (reference: python/paddle/nn/utils/)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor


def parameters_to_vector(parameters, name=None):
    from ...tensor.manipulation import concat
    return concat([p.reshape([-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset:offset + n].reshape(p._data.shape))
        offset += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p._grad is not None]
    if not params:
        return Tensor(jnp.zeros([]))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p._grad)) for p in params]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(p._grad), norm_type))
                              for p in params), 1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p._grad = p._grad * clip_coef.astype(p._grad.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    for p in params:
        if p._grad is not None:
            p._grad = jnp.clip(p._grad, -clip_value, clip_value)


def weight_norm(layer, name="weight", dim=0):
    """Weight-norm reparameterization (reference: nn/utils/weight_norm_hook.py)."""
    import numpy as np
    from ...core.tensor import Parameter
    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != dim) if dim is not None else None
    g = jnp.linalg.norm(np.asarray(w._data), axis=axes, keepdims=True) if axes \
        else jnp.linalg.norm(np.asarray(w._data))
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g)))
    layer.add_parameter(name + "_v", Parameter(w._data))
    del layer._parameters[name]

    def hook(l, inputs):
        from ...core.tensor import apply
        v = l._parameters[name + "_v"]
        gg = l._parameters[name + "_g"]

        def f(vv, ggg):
            n = jnp.linalg.norm(vv, axis=axes, keepdims=True) if axes is not None \
                else jnp.linalg.norm(vv)
            return vv * (ggg / jnp.maximum(n, 1e-12))
        object.__setattr__(l, "_wn_cache", apply(f, v, gg))
        # place computed weight where forward finds it
        l.__dict__.setdefault("_wn_name", name)
        l._buffers.pop(name, None)
        object.__setattr__(l, name, l._wn_cache)
    layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    from ...core.tensor import Parameter, apply

    def f(vv, gg):
        import numpy as np
        axes = tuple(i for i in range(vv.ndim) if i != 0)
        n = jnp.linalg.norm(vv, axis=axes, keepdims=True)
        return vv * (gg / jnp.maximum(n, 1e-12))
    layer.add_parameter(name, Parameter(f(v._data, g._data)))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Attach spectral normalization to ``layer.<name>`` (reference
    nn/utils/spectral_norm_hook.py): a forward pre-hook renormalizes the
    weight by its largest singular value (power iteration) before every
    call.  Returns the layer."""
    from ..layer.norm import SpectralNorm

    w = getattr(layer, name)
    if dim is None:
        # conv-transpose weights store (in, out, ...) — normalize along 1
        dim = 1 if type(layer).__name__ in (
            "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
            "Linear") else 0
    sn = SpectralNorm(list(w.shape), dim=dim, power_iters=n_power_iterations,
                      epsilon=eps)
    layer.add_sublayer(f"{name}_spectral_norm", sn)
    # reparametrize: the trainable param moves to <name>_orig; <name>
    # becomes a plain attribute recomputed from it before every forward
    # (so optimizers update the raw weight, never the normalized view)
    del layer._parameters[name]
    setattr(layer, name + "_orig", w)

    def pre_hook(lyr, inputs):
        object.__setattr__(lyr, name, sn(getattr(lyr, name + "_orig")))
        return None

    layer.register_forward_pre_hook(pre_hook)
    pre_hook(layer, None)  # valid immediately, not just after first call
    return layer
