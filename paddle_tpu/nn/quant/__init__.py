"""nn.quant namespace (reference: python/paddle/nn/quant/) — re-exports the
quantization building blocks under their nn-side names."""

from ...quantization import (AbsMaxObserver, ImperativeQuantAware,  # noqa: F401
                             MovingAverageAbsMaxObserver, QuantedLinear,
                             fake_quant_dequant)

FakeQuantAbsMax = AbsMaxObserver  # reference class name for the observer

__all__ = ["QuantedLinear", "fake_quant_dequant", "AbsMaxObserver",
           "FakeQuantAbsMax", "MovingAverageAbsMaxObserver",
           "ImperativeQuantAware"]
