"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant, Normal, XavierUniform
from .base import Layer


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Linear(Layer):
    """y = xW + b, weight shape (in_features, out_features) — matches the
    reference's Linear (nn/layer/common.py) so state_dicts are interchangeable."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        if padding_idx is not None:
            pid = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            self.weight._data = self.weight._data.at[pid].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, input):
        from ...tensor.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, input):
        return F.dropout(input, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout2d(input, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout3d(input, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, self.p, self.training)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            [1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self._pad = padding if isinstance(padding, (list, tuple)) else [padding]
        self._mode, self._value, self._data_format = mode, value, data_format

    def forward(self, x):
        return F.pad(x, self._pad, self._mode, self._value, self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...core.tensor import apply
        return apply(lambda a, b: jnp.linalg.norm(a - b + self.epsilon, ord=self.p,
                                                  axis=-1, keepdims=self.keepdim), x, y)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ...core.tensor import apply
        def f(a):
            ax = self.axis % a.ndim
            return a.reshape(a.shape[:ax] + tuple(self.shape) + a.shape[ax + 1:])
        return apply(f, x)
