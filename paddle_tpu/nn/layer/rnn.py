"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py, operators/rnn_op.*).

TPU-first: the time loop is ``jax.lax.scan`` (compiled once, no Python loop),
weights follow paddle's layout (weight_ih: (gates*hidden, input)) so
state_dicts interchange.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ..initializer import Uniform
from .base import Layer
from .containers import LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        return Tensor(jnp.full((batch, self.hidden_size), init_value, self._dtype))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out, out
        out, new = apply(f, inputs, states, self.weight_ih, self.weight_hh,
                         self.bias_ih, self.bias_hh)
        return out, new

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = (self.get_initial_states(inputs), self.get_initial_states(inputs))
        h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i, fgt, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fgt), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = fgt * cc + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply(f, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh)
        return new_h, (new_h, new_c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        new_h = apply(f, inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh)
        return new_h, new_h

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_rnn(mode, x, init_states, params, time_major, reverse=False, seq_lens=None):
    """Run one direction of one layer with lax.scan.  x: (B,T,I) or (T,B,I)."""
    def f(a, h0, c0, lens, wi, wh, bi, bh):
        xs = a if time_major else jnp.swapaxes(a, 0, 1)  # (T,B,I)
        T = xs.shape[0]
        if reverse:
            xs = jnp.flip(xs, 0)

        def step(carry, inp):
            x_t, t = inp
            h, c = carry
            if mode == "LSTM":
                gates = x_t @ wi.T + bi + h @ wh.T + bh
                i, fgt, g, o = jnp.split(gates, 4, axis=-1)
                i, fgt, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fgt), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                nc = fgt * c + i * g
                nh = o * jnp.tanh(nc)
            elif mode == "GRU":
                hg = h @ wh.T + bh
                xg = x_t @ wi.T + bi
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                nh = (1 - z) * n + z * h
                nc = c
            else:
                act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
                nh = act(x_t @ wi.T + bi + h @ wh.T + bh)
                nc = c
            if lens is not None:
                tt = (T - 1 - t) if reverse else t
                valid = (tt < lens)[:, None]
                nh = jnp.where(valid, nh, h)
                nc = jnp.where(valid, nc, c)
            return (nh, nc), nh
        c_init = c0 if c0 is not None else jnp.zeros_like(h0)
        (hT, cT), outs = jax.lax.scan(step, (h0, c_init),
                                      (xs, jnp.arange(T)))
        if reverse:
            outs = jnp.flip(outs, 0)
        outs = outs if time_major else jnp.swapaxes(outs, 0, 1)
        return outs, hT, cT
    h0, c0 = init_states
    return apply(f, x, h0, c0, seq_lens, *params)


class RNNBase(LayerList):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode, self.input_size, self.hidden_size = mode, input_size, hidden_size
        self.num_layers, self.time_major, self.dropout = num_layers, time_major, dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        self.num_directions = num_dirs
        gates = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                suffix = "_reverse" if d == 1 else ""
                wi = self.create_parameter([gates * hidden_size, in_sz], weight_ih_attr,
                                           default_initializer=init)
                wh = self.create_parameter([gates * hidden_size, hidden_size],
                                           weight_hh_attr, default_initializer=init)
                bi = self.create_parameter([gates * hidden_size], bias_ih_attr,
                                           is_bias=True, default_initializer=init)
                bh = self.create_parameter([gates * hidden_size], bias_hh_attr,
                                           is_bias=True, default_initializer=init)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        num_dirs = self.num_directions
        state_shape = (self.num_layers * num_dirs, batch, self.hidden_size)
        if initial_states is None:
            z = Tensor(jnp.zeros(state_shape, self._dtype))
            initial_states = (z, Tensor(jnp.zeros(state_shape, self._dtype))) \
                if self.mode == "LSTM" else z
        if self.mode == "LSTM":
            h_all, c_all = initial_states
        else:
            h_all, c_all = initial_states, None

        out = inputs
        final_h, final_c = [], []
        from .. import functional as F
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(num_dirs):
                idx = layer * num_dirs + d
                h0 = h_all[idx]
                c0 = c_all[idx] if c_all is not None else None
                outs, hT, cT = _scan_rnn(self.mode, out, (h0, c0),
                                         self._all_weights[idx], self.time_major,
                                         reverse=(d == 1), seq_lens=sequence_length)
                dir_outs.append(outs)
                final_h.append(hT)
                final_c.append(cT)
            if num_dirs == 2:
                from ...tensor.manipulation import concat
                out = concat(dir_outs, axis=-1)
            else:
                out = dir_outs[0]
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        from ...tensor.manipulation import stack
        h_stack = stack(final_h, axis=0)
        if self.mode == "LSTM":
            c_stack = stack(final_c, axis=0)
            return out, (h_stack, c_stack)
        return out, h_stack


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        kwargs.pop("proj_size", None)
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class RNN(Layer):
    """Wraps a cell into a recurrent network over time (reference rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse, self.time_major = is_reverse, time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        outputs = []
        states = initial_states
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in steps:
            x_t = inputs[:, t] if not self.time_major else inputs[t]
            out, states = self.cell(x_t, states, **kwargs)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        from ...tensor.manipulation import stack
        return stack(outputs, axis=time_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length, **kwargs)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length, **kwargs)
        from ...tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
