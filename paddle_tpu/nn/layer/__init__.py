from .activation import *  # noqa: F401,F403
from .base import Layer  # noqa: F401
from .common import *  # noqa: F401,F403
from .containers import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,  # noqa: F401
                   Conv3DTranspose)
from .loss import *  # noqa: F401,F403
from .moe import MoELayer  # noqa: F401
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,  # noqa: F401
                   InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
                   LocalResponseNorm, SpectralNorm, SyncBatchNorm)
from .pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,  # noqa: F401
                      AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
                      AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
                      MaxUnPool2D)
from .rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, RNNCellBase, SimpleRNN,  # noqa: F401
                  SimpleRNNCell)
from .transformer import (MultiHeadAttention, Transformer, TransformerDecoder,  # noqa: F401
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)
