"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

The attention core routes through paddle_tpu.ops.attention (Pallas flash
attention on TPU, XLA fallback elsewhere).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from .. import functional as F
from .base import Layer
from .common import Dropout, Linear
from .containers import LayerList
from .norm import LayerNorm


def _convert_attn_mask(mask, dtype):
    if mask is None:
        return None

    def f(m):
        if m.dtype == jnp.bool_:
            return jnp.where(m, 0.0, -1e9).astype(dtype)
        return m.astype(dtype)
    return apply(f, mask)


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.dropout, self.need_weights = dropout, need_weights
        self.head_dim = embed_dim // num_heads
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        def f(a):
            B, L, _ = a.shape
            return a.reshape(B, L, self.num_heads, self.head_dim)
        return apply(f, x)

    def gen_cache(self, key, value=None, type=Cache):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        B = key.shape[0]
        shape = (B, 0, self.num_heads, self.head_dim)
        return self.Cache(Tensor(jnp.zeros(shape, self._dtype)),
                          Tensor(jnp.zeros(shape, self._dtype)))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))  # B,L,H,D
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                from ...tensor.manipulation import concat
                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        mask = _convert_attn_mask(attn_mask, self._dtype)
        from ...ops.attention import scaled_dot_product_attention
        if self.need_weights:
            out, weights = self._attention_with_weights(q, k, v, mask)
        else:
            out = scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                               dropout_p=self.dropout,
                                               training=self.training)
            weights = None

        def merge(a):
            B, L = a.shape[0], a.shape[1]
            return a.reshape(B, L, self.embed_dim)
        out = self.out_proj(apply(merge, out))
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and not isinstance(cache, self.StaticCache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)

    def _attention_with_weights(self, q, k, v, mask):
        def f(qq, kk, vv, m):
            scale = 1.0 / jnp.sqrt(qq.shape[-1]).astype(qq.dtype)
            scores = jnp.einsum("blhd,bmhd->bhlm", qq, kk) * scale
            if m is not None:
                scores = scores + m
            w = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bhlm,bmhd->blhd", w, vv)
            return o, w
        out, w = apply(f, q, k, v, mask)
        if self.dropout and self.training:
            out = F.dropout(out, self.dropout, training=True)
        return out, w


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [_clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] +
                                [_clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None,
                 act_dropout=None, normalize_before=False, weight_attr=None,
                 bias_attr=None, custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model, self.nhead = d_model, nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout,
                                                normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout,
                                                normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return Tensor(jnp.triu(jnp.full((length, length), -jnp.inf, jnp.float32), k=1))


def _clone_layer(layer):
    import copy
    cls = type(layer)
    new = cls.__new__(cls)
    Layer.__init__(new)
    for k, v in layer.__dict__.items():
        if k in ("_parameters", "_buffers", "_sub_layers", "_forward_pre_hooks",
                 "_forward_post_hooks", "_full_name"):
            continue
        new.__dict__[k] = v
    for name, p in layer._parameters.items():
        from ...core.tensor import Parameter
        new._parameters[name] = Parameter(jnp.array(p._data), trainable=p.trainable)
    for name, b in layer._buffers.items():
        new._buffers[name] = Tensor(jnp.array(b._data)) if b is not None else None
    for name, sub in layer._sub_layers.items():
        new._sub_layers[name] = _clone_layer(sub)
    return new
