"""Layer base class.

Reference: python/paddle/fluid/dygraph/layers.py:81 (``Layer``, 1612 lines) —
parameter/buffer registries, sublayer tree, forward hooks, state_dict
naming contract, train/eval mode.

TPU-native addition: :meth:`raw_state` / :meth:`bind` — the functional bridge
that lets the same ``forward`` run under ``jax.jit`` over an explicit
parameter pytree (this replaces the reference's dygraph-to-static AST
transpiler for the common case; see paddle_tpu.jit).
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dtype import convert_dtype, get_default_dtype
from ...core.tensor import Parameter, Tensor

_layer_counters: Dict[str, int] = {}


def _unique_name(prefix: str) -> str:
    idx = _layer_counters.get(prefix, 0)
    _layer_counters[prefix] = idx + 1
    return f"{prefix}_{idx}"


class HookRemoveHelper:
    def __init__(self, hooks: dict, hid: int):
        # guarded-by: none (hook registration/removal is module-build-time,
        # single-threaded; pool-task label is unique-name over-approximation)
        self._hooks = hooks
        self._hid = hid

    def remove(self):
        self._hooks.pop(self._hid, None)


class Layer:
    """Base class for all neural network layers."""

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype is not None else get_default_dtype()
        self._full_name = _unique_name(name_scope or self.__class__.__name__.lower())
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        # guarded-by: none (layer trees are built and mutated on one thread
        # before serving; thread labels here are unique-name over-approximation)
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0

    # ------------------------------------------------------------------ attrs
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            _remove_from(name, buffers, layers, self.__dict__)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            _remove_from(name, params, buffers, self.__dict__)
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for reg in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(reg)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for reg in (self._parameters, self._buffers, self._sub_layers):
            if name in reg:
                del reg[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) \
            + list(self._sub_layers)

    # ----------------------------------------------------------- registration
    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))

    def create_parameter(self, shape, attr=None, dtype=None, is_bias: bool = False,
                         default_initializer=None) -> Parameter:
        from ..initializer import Constant, XavierUniform
        from ...framework.param_attr import ParamAttr
        attr = ParamAttr._to_attr(attr)
        dtype = convert_dtype(dtype) if dtype is not None else self._dtype
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        else:
            # an explicit ParamAttr initializer wins; otherwise the
            # process-wide global outranks the layer's own default
            # (reference: fluid/initializer.py set_global_initializer)
            from ..initializer import get_global_initializer
            glob = get_global_initializer()
            if glob is not None:
                init = glob[1] if is_bias else glob[0]
            if init is None:
                init = default_initializer
            if init is None:
                init = Constant(0.0) if is_bias else XavierUniform()
        data = init(shape, dtype)
        p = Parameter(data, name=(attr.name if attr is not None else None),
                      trainable=(attr.trainable if attr is not None else True))
        if attr is not None:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
            p.do_model_average = getattr(attr, "do_model_average", None)
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros([0], convert_dtype(dtype) if dtype else self._dtype))

    # ------------------------------------------------------------- traversal
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        for name, layer in self._traverse(prefix, True):
            if name == prefix and not include_self:
                continue
            yield name, layer

    def _traverse(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self) -> str:
        return self._full_name

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix, include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(structured_name_prefix, include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate_owner(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    to_static_state_dict = state_dict

    def _locate_owner(self, qualified: str) -> Optional["Layer"]:
        parts = qualified.split(".")[:-1]
        layer: "Layer" = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        missing, unexpected = [], []
        own = self.state_dict()
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
                if list(arr.shape) != list(target.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: loaded {list(arr.shape)} vs "
                        f"expected {list(target.shape)}")
                target.set_value(jnp.asarray(arr))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ------------------------------------------------------------ mode/hooks
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # --------------------------------------------------------------- running
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ----------------------------------------------------------- conversions
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._convert_dtype(convert_dtype(dtype))
        return self

    def _convert_dtype(self, dtype):
        for l in self.sublayers(include_self=True):
            l._dtype = dtype
            for p in l._parameters.values():
                if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                    p._data = p._data.astype(dtype)
            for b in l._buffers.values():
                if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                    b._data = b._data.astype(dtype)

    def float(self):
        return self.astype(jnp.float32)

    def bfloat16(self):
        return self.astype(jnp.bfloat16)

    def half(self):
        return self.astype(jnp.float16)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ------------------------------------------------- functional bridge (TPU)
    def raw_state(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Extract (params, buffers) as raw-array pytrees keyed by state name."""
        params = {n: p._data for n, p in self.named_parameters() if p.trainable}
        buffers = {n: b._data for n, b in self.named_buffers() if b is not None}
        # non-trainable params ride with buffers so they are still bound
        for n, p in self.named_parameters():
            if not p.trainable:
                buffers[f"__frozen__.{n}"] = p._data
        return params, buffers

    @contextlib.contextmanager
    def bind(self, params: Dict[str, Any], buffers: Optional[Dict[str, Any]] = None,
             trainable_as_tensor: bool = True):
        """Temporarily swap parameter/buffer storage with the given pytrees.

        Inside a jit trace the pytrees are tracers; ``forward`` then executes
        as a pure function of them.  On exit, mutated buffer values can be
        read back with :meth:`read_buffers` before storage is restored.
        """
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved = {}
        try:
            for n, v in params.items():
                t = named_p[n]
                saved[id(t)] = (t, t._data)
                t._data = v
            if buffers:
                for n, v in buffers.items():
                    if n.startswith("__frozen__."):
                        t = named_p[n[len("__frozen__."):]]
                    else:
                        t = named_b[n]
                    saved[id(t)] = (t, t._data) if id(t) not in saved else saved[id(t)]
                    t._data = v
            yield self
        finally:
            for t, old in saved.values():
                t._data = old

    def read_buffers(self, buffers: Dict[str, Any]) -> Dict[str, Any]:
        """Read current (possibly trace-mutated) values of the named buffers."""
        named_b = dict(self.named_buffers())
        named_p = dict(self.named_parameters())
        out = {}
        for n in buffers:
            if n.startswith("__frozen__."):
                out[n] = named_p[n[len("__frozen__."):]]._data
            else:
                out[n] = named_b[n]._data
        return out


def _remove_from(name, *dicts):
    for d in dicts:
        if d is not None and name in d:
            del d[name]
