"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .base import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_features], attr=weight_attr,
                                                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], self._dtype)))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid-era BatchNorm (acts like eval-aware BatchNorm with act)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=False, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch statistics are synchronized automatically when the batch
    axis is sharded over the mesh (GSPMD inserts the cross-replica reduction);
    eager single-process behaves as BatchNorm.  Kept for API parity with
    nn.SyncBatchNorm (reference: python/paddle/nn/layer/norm.py:1034)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        mapping = {}
        def convert(l):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                    new = SyncBatchNorm(sub._num_features, sub._momentum, sub._epsilon,
                                        data_format=sub._data_format)
                    new.weight, new.bias = sub.weight, sub.bias
                    new._buffers.update(sub._buffers)
                    l._sub_layers[name] = new
                else:
                    convert(sub)
        convert(layer)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr,
                                                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr,
                                              is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._num_channels = num_groups, num_channels
        self._epsilon, self._data_format = epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_features, self._epsilon = num_features, epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter([num_features], attr=weight_attr,
                                               default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self._dim, self._power_iters, self._epsilon = dim, power_iters, epsilon
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        # buffers, not parameters: the power-iteration state must persist
        # through jitted steps (functionalize writes buffers back; params
        # would be restored on exit and u/v would never advance under jit)
        self.register_buffer("weight_u", Tensor(Normal(0, 1)([h], "float32")))
        self.register_buffer("weight_v", Tensor(Normal(0, 1)([w], "float32")))

    def forward(self, weight):
        from ...core.tensor import apply
        import jax
        dim, iters, eps = self._dim, self._power_iters, self._epsilon

        def f(w, u, v):
            w_m = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = w_m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = w_m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ w_m @ v
            return w / sigma, u, v

        out, u_new, v_new = apply(f, weight, self.weight_u, self.weight_v)
        # persist the power-iteration state (reference SpectralNormOp writes
        # U/V back every forward) so iters=1 converges across training steps
        self.weight_u._data = jax.lax.stop_gradient(
            getattr(u_new, "_data", u_new))
        self.weight_v._data = jax.lax.stop_gradient(
            getattr(v_new, "_data", v_new))
        return out
