"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .base import Layer


def _simple(name, fn_name=None, **defaults):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                merged[keys[i]] = a
            merged.update({k: v for k, v in kwargs.items() if k != "name"})
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    return _Act


CELU = _simple("CELU", "celu", alpha=1.0)
ELU = _simple("ELU", "elu", alpha=1.0)
GELU = _simple("GELU", "gelu", approximate=False)
Hardshrink = _simple("Hardshrink", "hardshrink", threshold=0.5)
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", min=-1.0, max=1.0)
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
LeakyReLU = _simple("LeakyReLU", "leaky_relu", negative_slope=0.01)
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Maxout = _simple("Maxout", "maxout", groups=2, axis=1)
Mish = _simple("Mish", "mish")
ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
SELU = _simple("SELU", "selu")
Sigmoid = _simple("Sigmoid", "sigmoid")
Silu = _simple("Silu", "silu")
Softplus = _simple("Softplus", "softplus", beta=1.0, threshold=20.0)
Softshrink = _simple("Softshrink", "softshrink", threshold=0.5)
Softsign = _simple("Softsign", "softsign")
Swish = _simple("Swish", "swish")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu", threshold=1.0)
Softmax = _simple("Softmax", "softmax", axis=-1)
LogSoftmax = _simple("LogSoftmax", "log_softmax", axis=-1)
GLU = _simple("GLU", "glu", axis=-1)
RReLU = _simple("RReLU", "rrelu", lower=0.125, upper=0.3333333)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
