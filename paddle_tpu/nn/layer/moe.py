"""Mixture-of-Experts layer (dygraph-style wrapper over ops.moe).

The reference ships the EP transport (global_scatter/global_gather,
distributed/utils.py:57,179) but keeps the gate + MoE layer in downstream
repos; this build provides both.  Experts are a stacked parameter pytree
(E leading dim) so expert parallelism is just a sharding annotation on the
expert axis — no per-expert Python modules to keep in sync.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import rng
from ...core.tensor import Tensor, apply
from ..initializer import Normal
from .base import Layer


class MoELayer(Layer):
    """Top-k routed mixture of expert FFNs.

    Args:
      d_model: token hidden size.
      d_hidden: expert FFN intermediate size.
      num_experts: total experts (global, across the expert mesh axis).
      top_k: experts per token (1 = Switch, 2 = GShard).
      capacity_factor: per-expert buffer slack.
      expert_axis: mesh axis experts shard over (set via ``set_mesh``).
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 expert_axis: str = "data", gate_jitter: bool = False,
                 activation=jax.nn.gelu, index_dispatch: bool = True,
                 name=None):
        super().__init__()
        self.d_model, self.d_hidden = d_model, d_hidden
        self.num_experts, self.top_k = num_experts, top_k
        self.capacity_factor = capacity_factor
        self.expert_axis = expert_axis
        self.gate_jitter = gate_jitter
        self.activation = activation
        self.index_dispatch = index_dispatch  # gather/scatter vs einsum masks
        self._mesh = None
        E, H, I = num_experts, d_model, d_hidden
        init = Normal(0.0, 0.02)
        self.gate_weight = self.create_parameter([H, E], default_initializer=init)
        self.expert_w1 = self.create_parameter([E, H, I], default_initializer=init)
        self.expert_b1 = self.create_parameter(
            [E, I], default_initializer=lambda s, d: jnp.zeros(s, d))
        self.expert_w2 = self.create_parameter([E, I, H], default_initializer=init)
        self.expert_b2 = self.create_parameter(
            [E, H], default_initializer=lambda s, d: jnp.zeros(s, d))
        self.aux_loss = None  # set on every forward

    def set_mesh(self, mesh):
        """Enable expert parallelism over ``self.expert_axis`` of ``mesh``."""
        self._mesh = mesh
        return self

    def forward(self, x):
        from ...ops.moe import moe_ffn, moe_ffn_indices
        ffn = moe_ffn_indices if self.index_dispatch else moe_ffn
        jitter_key = rng.next_key() if (self.gate_jitter and self.training) else None

        def f(x_, gw, w1, b1, w2, b2):
            shape = x_.shape
            tokens = x_.reshape(-1, self.d_model)
            out, aux = ffn(tokens, gw, w1, b1, w2, b2, k=self.top_k,
                               capacity_factor=self.capacity_factor,
                               mesh=self._mesh, expert_axis=self.expert_axis,
                               jitter_key=jitter_key, activation=self.activation)
            return out.reshape(shape), aux

        out, aux = apply(f, x, self.gate_weight, self.expert_w1, self.expert_b1,
                         self.expert_w2, self.expert_b2)
        self.aux_loss = aux
        return out

    def extra_repr(self):
        return (f"d_model={self.d_model}, d_hidden={self.d_hidden}, "
                f"num_experts={self.num_experts}, top_k={self.top_k}")
