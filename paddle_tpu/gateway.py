"""Serving gateway: the front door for a fleet of serving-engine replicas.

PRs 1-7 built everything *behind* the socket — ragged/paged/speculative
engines, AOT-warmed compile caches, telemetry, a live ops endpoint — but
``add_request`` has no deadline, no cancel, no backpressure, and nothing
routes across more than one engine.  :class:`ServingGateway` is that
missing subsystem: it fronts N engine replicas (any mix of the five engine
classes in ``paddle_tpu.serving``) and turns a fast engine into a service
that stays fast under overload, replica stalls, and rolling restarts.

Four disciplines, each host-side only (no compiled program changes):

**Admission control & load shedding.**  Requests wait in bounded
per-priority queues (priority 0 is served first).  Each priority bounds
both queue DEPTH (``max_queue_depth``) and queued TOKEN budget
(``max_queued_tokens`` — prompt + ``max_new_tokens`` per request, the
token-budget-aware limit: a queue of 8 huge prompts is as overloaded as a
queue of 800 small ones).  Past either limit ``submit()`` rejects
IMMEDIATELY with a structured :class:`Overloaded` result — the client gets
a retryable signal in O(1) instead of a admission that silently grows
everyone's tail latency.

**Deadlines & cancellation.**  ``submit(..., ttft_deadline_s=,
deadline_s=)`` bounds time-to-first-token and total latency.  The dispatch
loop expires overdue QUEUED requests before they ever touch an engine, and
cancels overdue IN-FLIGHT ones through the ``Engine.cancel(rid)``
primitive (slots / KV blocks / prefix pins / sampling rows all released;
serving.py).  Expired requests carry a structured
:class:`DeadlineExceeded`; streaming consumers get the terminal
``on_token(gid, None, True)`` end-of-stream either way.
``gateway.cancel(gid)`` is the client-initiated form of the same path.

**Replica routing.**  Default policy is least-outstanding-tokens (the
replica with the smallest Σ of prompt + remaining-budget tokens in
flight).  Replicas with a warm prefix cache get an AFFINITY override:
requests whose prompt chain-digest prefix matches cached blocks route to
that replica (deepest match wins; ties fall back to least-outstanding) —
shared system prompts keep hitting the replica that already holds their
k/v.  Health is watched per the PR 7 ``/healthz`` stall logic: a replica
whose tracer's newest event is older than ``stall_threshold_s`` while it
holds in-flight work is QUARANTINED — its completed requests are
harvested, and every other in-flight request is re-admitted elsewhere
after the documented replay signal ``on_token(gid, None, False)``
(discard the streamed prefix; the rerun re-delivers from token one).

**Graceful drain.**  ``drain(name)`` stops admission to a replica while
its in-flight requests run to completion (zero drops); optionally a
``replacement`` engine is AOT-``warmup()``-ed against a ``cache_dir``
(PR 6) while the old replica drains, and takes traffic the moment the
drain completes — the rolling-restart primitive.

The gateway is COOPERATIVE and single-threaded, like the engines it
fronts: ``step()`` runs one round (health → expiry → drains → dispatch →
replica steps → harvest → in-flight deadlines), and ``run_to_completion``
drives it.  With a ``tracer=`` it emits ``gateway`` events
(shed/expired/dispatch/reroute/quarantine/drain) through the PR 2 Tracer
— ring buffer, ``summary()``, Prometheus, and chrome exports included —
and ``ops_server.OpsServer.attach(gateway)`` serves the live
``/gateway`` view.

Typical use::

    gw = ServingGateway(tracer=Tracer())
    gw.add_replica(engine_a, "a")
    gw.add_replica(engine_b, "b")
    req = gw.submit([12, 71, 9], max_new_tokens=32, ttft_deadline_s=0.5)
    if req.status == "shed":
        ...                         # req.error is a structured Overloaded
    while gw.pending():
        gw.step()
    assert req.status == "finished" and req.tokens

No reference counterpart: the reference snapshot serves static batches
with no service layer at all (SURVEY §2.3); this is the serving-system
capstone over the beyond-reference engines.
"""

from __future__ import annotations

import collections
import itertools
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils.stats import (DEFAULT_TIME_BUCKETS, StatRegistry,
                          prometheus_text as _prometheus_text)

__all__ = ["ServingGateway", "GatewayRequest", "Replica", "Overloaded",
           "DeadlineExceeded"]

#: replica lifecycle states
ACTIVE = "active"
DRAINING = "draining"
QUARANTINED = "quarantined"
STOPPED = "stopped"

#: gateway-request terminal states (plus the live "queued"/"dispatched")
_TERMINAL = frozenset({"finished", "shed", "expired", "cancelled",
                       "failed"})


class Overloaded:
    """Structured shed rejection: the queue the request would have joined
    was over its depth or token budget.  Returned on ``GatewayRequest
    .error`` with ``status == "shed"`` — never an exception, never a
    silent drop: the client sees exactly which limit fired and how deep
    the queue was, the retryable-backpressure contract."""

    __slots__ = ("priority", "queue_depth", "queued_tokens", "est_tokens",
                 "max_queue_depth", "max_queued_tokens")

    def __init__(self, priority, queue_depth, queued_tokens, est_tokens,
                 max_queue_depth, max_queued_tokens):
        self.priority = priority
        self.queue_depth = queue_depth
        self.queued_tokens = queued_tokens
        self.est_tokens = est_tokens
        self.max_queue_depth = max_queue_depth
        self.max_queued_tokens = max_queued_tokens

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"Overloaded(priority={self.priority}, "
                f"queue_depth={self.queue_depth}/{self.max_queue_depth}, "
                f"queued_tokens={self.queued_tokens}"
                f"{'' if self.max_queued_tokens is None else '/' + str(self.max_queued_tokens)})")


class DeadlineExceeded:
    """Structured deadline expiry: ``kind`` is ``"ttft"`` (no first token
    by ``ttft_deadline_s``) or ``"total"`` (``deadline_s`` elapsed).
    ``tokens_delivered`` counts what the consumer already streamed —
    a mid-decode total-deadline cancel keeps the partial output on
    ``GatewayRequest.tokens``."""

    __slots__ = ("kind", "deadline_s", "waited_s", "tokens_delivered")

    def __init__(self, kind, deadline_s, waited_s, tokens_delivered):
        self.kind = kind
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        self.tokens_delivered = tokens_delivered

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"DeadlineExceeded(kind={self.kind!r}, "
                f"deadline_s={self.deadline_s}, "
                f"waited_s={round(self.waited_s, 4)}, "
                f"tokens_delivered={self.tokens_delivered})")


class GatewayRequest:
    """One gateway-tracked request (host-side handle).  ``status`` walks
    ``queued`` → ``dispatched`` → ``finished``, or terminates early as
    ``shed`` / ``expired`` / ``cancelled`` / ``failed`` with the
    structured reason on ``error``.  Timestamps are the gateway's clock
    (injectable for tests)."""

    __slots__ = ("gid", "prompt", "max_new_tokens", "priority",
                 "ttft_deadline_s", "deadline_s", "sampling", "on_token",
                 "status", "tokens", "error", "replica", "engine_rid",
                 "submitted_at", "dispatched_at", "first_token_at",
                 "finished_at", "replays", "trace", "_rerouting",
                 "_pending_expiry")

    def __init__(self, gid, prompt, max_new_tokens, priority,
                 ttft_deadline_s, deadline_s, sampling, on_token,
                 submitted_at):
        self.gid = gid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.ttft_deadline_s = ttft_deadline_s
        self.deadline_s = deadline_s
        self.sampling = dict(sampling)
        self.on_token = on_token
        self.status = "queued"
        self.tokens: List[int] = []
        self.error = None
        self.replica: Optional[str] = None
        self.engine_rid: Optional[int] = None
        self.submitted_at = submitted_at
        self.dispatched_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.replays = 0
        # end-to-end trace identity (telemetry.TraceContext): the ROOT
        # span, minted at submit when the gateway traces; each dispatch
        # mints a child for that engine attempt
        self.trace = None
        self._rerouting = False
        self._pending_expiry: Optional[DeadlineExceeded] = None

    @property
    def done(self) -> bool:
        return self.status in _TERMINAL

    @property
    def est_tokens(self) -> int:
        """Queue-budget estimate: prompt plus full generation budget."""
        return len(self.prompt) + self.max_new_tokens

    def remaining_tokens(self) -> int:
        """Outstanding-work estimate for routing: whatever of the
        prompt+budget has not been delivered yet."""
        return max(self.est_tokens - len(self.tokens), 0)

    def to_dict(self) -> Dict[str, Any]:
        err = self.error
        return {"gid": self.gid, "status": self.status,
                "priority": self.priority, "replica": self.replica,
                "prompt_len": len(self.prompt),
                "max_new_tokens": self.max_new_tokens,
                "tokens": len(self.tokens), "replays": self.replays,
                "trace_id": (None if self.trace is None
                             else self.trace.trace_id),
                "error": (err.to_dict() if hasattr(err, "to_dict")
                          else err)}

    def __repr__(self):
        return (f"GatewayRequest(gid={self.gid}, status={self.status!r}, "
                f"replica={self.replica!r}, tokens={len(self.tokens)})")


class Replica:
    """One engine replica under gateway management: lifecycle state plus
    the gateway's view of its in-flight work (engine rid → request)."""

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self.state = ACTIVE
        self.inflight: Dict[int, GatewayRequest] = {}
        self.reason: Optional[str] = None          # quarantine reason
        self.replacement = None                    # (engine, name) draining
        self.warm_report = None

    def outstanding_tokens(self) -> int:
        return sum(r.remaining_tokens() for r in self.inflight.values())

    def slots_available(self) -> int:
        """Admission headroom: free engine slots not already spoken for by
        the engine's own internal queue (the gateway keeps waiting
        requests in ITS queues, where deadlines and shedding apply)."""
        eng = self.engine
        return len(eng._free_slots()) - len(eng._queue)

    def idle(self) -> bool:
        return not self.inflight and not self.engine.pending()

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "state": self.state,
                "inflight": len(self.inflight),
                "outstanding_tokens": self.outstanding_tokens(),
                "engine": type(self.engine).__name__,
                "reason": self.reason}


class ServingGateway:
    """Multi-replica serving front door (module docstring).

    ``max_queue_depth`` / ``max_queued_tokens``: per-priority admission
    bounds (None disables the token budget).  ``priorities``: number of
    priority classes (0 = highest, dispatched first).
    ``stall_threshold_s``: the PR 7 ``/healthz`` dial — a replica whose
    tracer shows no event for this long while holding in-flight work is
    quarantined.  ``tracer``: optional ``telemetry.Tracer`` for structured
    ``gateway`` events (None keeps every emit behind one attribute
    check).  ``clock``: monotonic-seconds callable — injectable so tests
    drive deadlines deterministically."""

    def __init__(self, replicas=None, *, max_queue_depth: int = 64,
                 max_queued_tokens: Optional[int] = None,
                 priorities: int = 2, stall_threshold_s: float = 30.0,
                 tracer=None, clock: Callable[[], float] = time.monotonic,
                 request_history: int = 4096,
                 logger: Optional[logging.Logger] = None):
        if int(priorities) < 1:
            raise ValueError("priorities must be >= 1")
        if int(max_queue_depth) < 1:
            raise ValueError("max_queue_depth must be >= 1")
        self.max_queue_depth = int(max_queue_depth)
        self.max_queued_tokens = (None if max_queued_tokens is None
                                  else int(max_queued_tokens))
        self.priorities = int(priorities)
        self.stall_threshold_s = float(stall_threshold_s)
        self.tracer = tracer
        self._clock = clock
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._queues: List[collections.deque] = [
            collections.deque() for _ in range(self.priorities)]
        self._queued_tokens = [0] * self.priorities
        self._replicas: Dict[str, Replica] = {}
        # gid → handle while live, plus a BOUNDED tail of terminal
        # handles for late cancel()/request() lookups — a long-lived
        # gateway must not grow host memory per request served (the
        # caller's own handle from submit() stays valid regardless)
        self.request_history = int(request_history)
        # optional SLO monitor (telemetry_slo.SLOMonitor): gateway-level
        # TTFT samples and terminal counts forward into its windowed
        # stores behind one attribute check
        self._slo = None
        # optional engine factory (autoscaler scale-out spawns from it);
        # registered via register_replica_factory
        self._replica_factory: Optional[Callable[[], Any]] = None
        self._requests: Dict[int, GatewayRequest] = {}
        self._terminal_order: collections.deque = collections.deque()
        self._finished: Dict[int, List[int]] = {}
        self._gids = itertools.count()
        self._stats = StatRegistry()
        self._stats.histogram("queue_seconds", DEFAULT_TIME_BUCKETS)
        self._stats.histogram("ttft_seconds", DEFAULT_TIME_BUCKETS)
        for engine in (replicas or []):
            self.add_replica(engine)

    # ------------------------------------------------------------ fleet --

    def add_replica(self, engine, name: Optional[str] = None) -> str:
        """Register an engine replica (any of the five serving classes —
        it only needs the shared scheduling surface: ``add_request`` /
        ``step`` / ``pop_finished`` / ``cancel`` / ``pending``)."""
        if not hasattr(engine, "cancel"):
            raise TypeError(
                f"{type(engine).__name__} has no cancel(rid) — the gateway "
                f"needs the serving-engine cancellation primitive")
        if name is None:
            i = len(self._replicas)
            while f"r{i}" in self._replicas:     # auto-names never collide
                i += 1
            name = f"r{i}"
        if name in self._replicas and \
                self._replicas[name].state != STOPPED:
            raise ValueError(f"replica {name!r} already registered")
        self._replicas[name] = Replica(name, engine)
        self._stats.add("replicas_added")
        return name

    def remove_replica(self, name: str) -> Replica:
        """Deregister a STOPPED replica — the final step of an elastic
        scale-down (``drain`` without replacement leaves the stopped
        shell registered so ``is_drained`` stays answerable; a long-lived
        elastic fleet must not accumulate one dead entry per drain).
        Only stopped replicas may be removed: draining ones still hold
        work, and removing an active one would drop its in-flight
        bookkeeping."""
        rep = self.replica(name)
        if rep.state != STOPPED:
            raise ValueError(f"replica {name!r} is {rep.state}; only "
                             f"stopped replicas can be removed (drain it "
                             f"first)")
        del self._replicas[name]
        self._stats.add("replicas_removed")
        self._emit("removed", replica=name)
        return rep

    def register_replica_factory(self, factory: Optional[Callable[[], Any]]
                                 ) -> Optional[Callable[[], Any]]:
        """Register (or with None clear) the engine factory that elastic
        scale-out spawns replicas from — a zero-arg callable returning a
        FRESH engine (any of the five serving classes).  The gateway never
        calls it itself; ``autoscaler.ElasticAutoscaler`` does, then warms
        and ``add_replica``s the result."""
        if factory is not None and not callable(factory):
            raise TypeError(f"replica factory must be callable, got "
                            f"{factory!r}")
        self._replica_factory = factory
        return factory

    @property
    def replica_factory(self) -> Optional[Callable[[], Any]]:
        return self._replica_factory

    def replica(self, name: str) -> Replica:
        rep = self._replicas.get(name)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        return rep

    def replicas(self) -> List[Replica]:
        """Every registered replica (all lifecycle states) — the public
        fleet enumeration the autoscaler and ops views read."""
        return list(self._replicas.values())

    def replica_tracers(self) -> List[Tuple[str, Any]]:
        """(name, tracer) for every CURRENT replica engine that has one —
        the public enumeration ``ops_server`` pulls per ``/requests`` /
        ``/request/<id>`` query, so drain-swapped replacements feed the
        trace stitcher without re-attaching anything."""
        out = []
        for name, rep in list(self._replicas.items()):
            tr = getattr(rep.engine, "tracer", None)
            if tr is not None:
                out.append((name, tr))
        return out

    def quarantine(self, name: str, reason: str = "manual"):
        """Pull a replica out of rotation: completed requests are
        harvested, every other in-flight request is cancelled on the
        replica (host-side bookkeeping — safe even when the device is
        wedged) and re-admitted at the FRONT of its priority queue after
        the documented replay signal ``on_token(gid, None, False)``."""
        rep = self.replica(name)
        if rep.state in (QUARANTINED, STOPPED):
            return rep
        was_draining = rep.state == DRAINING
        rep.state = QUARANTINED
        rep.reason = reason
        self._stats.add("quarantines")
        self._emit("quarantine", replica=name, reason=reason,
                   inflight=len(rep.inflight))
        self._log.warning("gateway: quarantined replica %s (%s), "
                          "re-admitting %d in-flight request(s)",
                          name, reason, len(rep.inflight))
        self._reroute_inflight(rep)
        if was_draining:
            # a drain interrupted by quarantine still COMPLETES: the
            # rerouted work finishes elsewhere, and the (possibly already
            # warmed) replacement must not be silently dropped —
            # is_drained() stays answerable and drains_started/_completed
            # stay symmetric
            self._complete_drain(rep)
        return rep

    def reinstate(self, name: str):
        """Return a quarantined replica to rotation (operator decision —
        the gateway never auto-reinstates a replica it benched)."""
        rep = self.replica(name)
        if rep.state == QUARANTINED:
            rep.state = ACTIVE
            rep.reason = None
        return rep

    def drain(self, name: str, replacement=None,
              cache_dir: Optional[str] = None, warm: bool = True,
              replacement_name: Optional[str] = None):
        """Gracefully drain a replica: admission stops NOW, in-flight work
        runs to completion under ``step()``, and once idle the replica is
        STOPPED.  ``replacement``: an engine to take its place — with
        ``warm=True`` it is AOT-``warmup()``-ed immediately (optionally
        against ``cache_dir``, the PR 6 persistent compile cache) so it
        joins the fleet already compiled.  Returns the warmup report (or
        None)."""
        rep = self.replica(name)
        if rep.state == STOPPED:
            return rep.warm_report
        # validate the hand-over NOW, not rounds later inside step() when
        # the drain completes (by then the replacement reference would be
        # cleared and the fleet left a replica short)
        if replacement is not None:
            if not hasattr(replacement, "cancel"):
                raise TypeError(
                    f"{type(replacement).__name__} has no cancel(rid) — "
                    f"the gateway needs the serving-engine cancellation "
                    f"primitive")
            other = self._replicas.get(replacement_name)
            if other is not None and other is not rep \
                    and other.state != STOPPED:
                raise ValueError(
                    f"replacement name {replacement_name!r} is a live "
                    f"replica")
        rep.state = DRAINING
        rep.replacement = (replacement, replacement_name)
        self._stats.add("drains_started")
        self._emit("drain_start", replica=name,
                   inflight=len(rep.inflight),
                   replacement=replacement is not None)
        if replacement is not None and warm:
            try:
                rep.warm_report = replacement.warmup(cache_dir=cache_dir)
            except NotImplementedError as e:
                # TP/mesh engines compile on first dispatch (serving.py);
                # the swap still proceeds, just unwarmed
                self._log.debug("gateway: replacement warmup skipped: %r",
                                e)
        self._advance_drains()
        return rep.warm_report

    def is_drained(self, name: str) -> bool:
        return self.replica(name).state == STOPPED

    # --------------------------------------------------------- admission --

    def submit(self, prompt, max_new_tokens: int, *, priority: int = 0,
               ttft_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None, on_token=None,
               **sampling) -> GatewayRequest:
        """Admit (or shed) one request; always returns the
        :class:`GatewayRequest` handle.  A shed request is terminal on
        return: ``status == "shed"`` with a structured
        :class:`Overloaded` on ``error`` — and a streaming consumer gets
        the terminal ``on_token(gid, None, True)`` immediately, so no
        rejection is ever silent."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0 <= int(priority) < self.priorities:
            raise ValueError(f"priority must be in [0, {self.priorities})")
        now = self._clock()
        req = GatewayRequest(next(self._gids), prompt, max_new_tokens,
                             priority, ttft_deadline_s, deadline_s,
                             sampling, on_token, now)
        if self.tracer is not None:
            # mint the request's end-to-end trace: this root context is
            # THE trace_id every gateway event and (via per-dispatch
            # child spans) every engine-timeline event will carry
            from .telemetry import TraceContext
            req.trace = TraceContext.root()
        self._requests[req.gid] = req
        self._stats.add("submitted")
        if self._slo is not None:
            self._slo.count("submitted")
        self._emit("submit", gid=req.gid, priority=req.priority,
                   prompt_len=len(prompt),
                   max_new_tokens=req.max_new_tokens,
                   **self._trace_fields(req))
        q = self._queues[req.priority]
        qtok = self._queued_tokens[req.priority]
        over_depth = len(q) >= self.max_queue_depth
        over_tokens = (self.max_queued_tokens is not None
                       and qtok + req.est_tokens > self.max_queued_tokens)
        if over_depth or over_tokens:
            req.error = Overloaded(req.priority, len(q), qtok,
                                   req.est_tokens, self.max_queue_depth,
                                   self.max_queued_tokens)
            self._finalize(req, "shed", now)
            self._emit("shed", gid=req.gid, priority=req.priority,
                       queue_depth=len(q), queued_tokens=qtok,
                       over=("depth" if over_depth else "tokens"),
                       **self._trace_fields(req))
            return req
        q.append(req)
        self._queued_tokens[req.priority] += req.est_tokens
        return req

    def set_slo(self, slo):
        """Attach (or with None detach) a ``telemetry_slo.SLOMonitor``:
        submitted/terminal counts and gateway-level TTFT samples
        (submit → first surviving token) forward into its windowed
        stores — the inputs of the shed-rate and TTFT objectives."""
        self._slo = slo
        return slo

    @staticmethod
    def _trace_fields(req: GatewayRequest, ctx=None) -> Dict[str, Any]:
        """trace_id/span_id/parent_span_id fields for a request-scoped
        gateway event: the dispatch-attempt child when ``ctx`` is given,
        else the request's root span; {} for untraced requests."""
        if ctx is not None:
            return ctx.to_dict()
        if req.trace is None:
            return {}
        return req.trace.to_dict()

    def cancel(self, gid: int) -> bool:
        """Client-initiated cancellation: a queued request is removed and
        finalized here; a dispatched one rides ``Engine.cancel`` (exact
        resource release, terminal stream signal).  False: unknown or
        already terminal."""
        req = self._requests.get(gid)
        if req is None or req.done:
            return False
        if req.status == "queued":
            self._unqueue(req)
            self._finalize(req, "cancelled", self._clock())
            self._emit("cancel", gid=gid, where="queued",
                       **self._trace_fields(req))
            return True
        rep = self._replicas.get(req.replica)
        if rep is None or req.engine_rid is None:
            return False
        if rep.engine.cancel(req.engine_rid):
            # the engine's terminal on_token already finalized the handle
            self._emit("cancel", gid=gid, where="inflight",
                       replica=rep.name, **self._trace_fields(req))
            return True
        return False

    # -------------------------------------------------------- scheduling --

    def step(self):
        """One gateway round: health-check replicas, expire overdue queued
        requests, advance drains, dispatch to replicas, step every replica
        with work, harvest completions, enforce in-flight deadlines."""
        self._check_health()
        now = self._clock()
        self._expire_queued(now)
        self._advance_drains()
        self._dispatch(now)
        for rep in self._replicas.values():
            if rep.state in (ACTIVE, DRAINING) and rep.engine.pending():
                rep.engine.step()
        self._harvest()
        self._enforce_inflight_deadlines(self._clock())
        self._advance_drains()

    def pending(self) -> bool:
        if any(self._queues):
            return True
        return any(rep.inflight or (rep.state in (ACTIVE, DRAINING)
                                    and rep.engine.pending())
                   for rep in self._replicas.values())

    def run_to_completion(self, max_ticks: Optional[int] = None
                          ) -> Dict[int, List[int]]:
        """Drive ``step()`` until nothing is queued or in flight; returns
        ``pop_finished()``."""
        ticks = 0
        while self.pending():
            self.step()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(f"not done after {max_ticks} ticks")
        return self.pop_finished()

    def pop_finished(self) -> Dict[int, List[int]]:
        """Completed generations since the last pop: {gid: tokens}.  Only
        natural completions land here — shed/expired/cancelled requests
        terminate on their handle (``status`` + ``error``)."""
        out, self._finished = self._finished, {}
        return out

    def request(self, gid: int) -> GatewayRequest:
        req = self._requests.get(gid)
        if req is None:
            raise KeyError(f"unknown gateway request {gid}")
        return req

    # ----------------------------------------------------- step internals --

    def _check_health(self):
        """PR 7 ``/healthz`` stall logic applied per replica: in-flight
        work + a tracer whose newest event is older than the threshold =
        a stalled tick → quarantine.  An idle replica is never flagged
        (no work → no events is healthy), and a replica without a tracer
        is trusted (nothing to judge by)."""
        for rep in list(self._replicas.values()):
            if rep.state not in (ACTIVE, DRAINING) or not rep.inflight:
                continue
            tracer = getattr(rep.engine, "tracer", None)
            if tracer is None:
                continue
            try:
                age = tracer.last_event_age_s()
            except Exception as e:  # noqa: BLE001 — a broken tracer must
                # not take the dispatch loop down with it
                self._log.debug("gateway: health scan failed on %s: %r",
                                rep.name, e)
                continue
            if age is not None and age > self.stall_threshold_s:
                self.quarantine(rep.name,
                                reason=f"stalled tick ({age:.1f}s > "
                                       f"{self.stall_threshold_s:.1f}s)")

    def _expire_queued(self, now: float):
        for pri, q in enumerate(self._queues):
            if not q:
                continue
            keep = collections.deque()
            for req in q:
                waited = now - req.submitted_at
                kind = None
                if req.deadline_s is not None and waited > req.deadline_s:
                    kind = "total"
                elif (req.ttft_deadline_s is not None
                        and waited > req.ttft_deadline_s):
                    kind = "ttft"
                if kind is None:
                    keep.append(req)
                    continue
                self._queued_tokens[pri] -= req.est_tokens
                req.error = DeadlineExceeded(kind, req.deadline_s
                                             if kind == "total"
                                             else req.ttft_deadline_s,
                                             waited, 0)
                self._finalize(req, "expired", now)
                self._stats.add(f"expired_{kind}")
                self._emit("expired", gid=req.gid, kind=kind,
                           waited_s=waited, where="queued",
                           **self._trace_fields(req))
            self._queues[pri] = keep

    def _enforce_inflight_deadlines(self, now: float):
        for rep in self._replicas.values():
            for rid, req in list(rep.inflight.items()):
                waited = now - req.submitted_at
                kind = None
                if req.deadline_s is not None and waited > req.deadline_s:
                    kind = "total"
                elif (req.first_token_at is None
                        and req.ttft_deadline_s is not None
                        and waited > req.ttft_deadline_s):
                    kind = "ttft"
                if kind is None:
                    continue
                req._pending_expiry = DeadlineExceeded(
                    kind, req.deadline_s if kind == "total"
                    else req.ttft_deadline_s, waited, len(req.tokens))
                self._stats.add(f"expired_{kind}")
                self._emit("expired", gid=req.gid, kind=kind,
                           waited_s=waited, where="inflight",
                           replica=rep.name,
                           tokens_delivered=len(req.tokens),
                           **self._trace_fields(req))
                if not rep.engine.cancel(rid):
                    # lost the race with retirement: the engine finished
                    # it this very round — harvest delivers it, the
                    # deadline miss stays recorded as an event only
                    req._pending_expiry = None

    def _advance_drains(self):
        for rep in list(self._replicas.values()):
            if rep.state == DRAINING and rep.idle():
                self._complete_drain(rep)

    def _complete_drain(self, rep: Replica):
        rep.state = STOPPED
        self._stats.add("drains_completed")
        self._emit("drain_done", replica=rep.name)
        replacement, new_name = rep.replacement or (None, None)
        rep.replacement = None
        if replacement is not None:
            name = self.add_replica(replacement, name=new_name)
            self._emit("replaced", replica=rep.name, by=name)

    def _dispatch(self, now: float):
        """Move queued requests onto replicas, highest priority first,
        FIFO within a priority, while any replica has admission
        headroom."""
        for pri, q in enumerate(self._queues):
            while q:
                target = self._route(q[0])
                if target is None:
                    return              # fleet-wide: no headroom anywhere
                req = q.popleft()
                self._queued_tokens[pri] -= req.est_tokens
                self._dispatch_to(target, req, now)

    def _route(self, req: GatewayRequest) -> Optional[Replica]:
        """Pick the target replica: among ACTIVE replicas with admission
        headroom, the deepest prefix-cache match wins (prefix affinity);
        ties — including the common no-match case — go to the least
        outstanding tokens."""
        cands = [rep for rep in self._replicas.values()
                 if rep.state == ACTIVE and rep.slots_available() > 0]
        if not cands:
            return None
        scored = [(-self._prefix_depth(rep.engine, req.prompt),
                   rep.outstanding_tokens(), i)
                  for i, rep in enumerate(cands)]
        return cands[min(scored)[2]]

    @staticmethod
    def _prefix_depth(engine, prompt: List[int]) -> int:
        """Length (in blocks) of the prompt's chain-digest prefix already
        resident in the replica's prefix cache — a pure READ of the chain
        keys (no LRU touch, no pinning: ``_lookup_prefix`` does those at
        admission)."""
        if not getattr(engine, "prefix_caching", False):
            return 0
        try:
            from .jit.bucketing import select_bucket
            P = select_bucket(len(prompt), engine.buckets)
        except ValueError:
            return 0
        pad = P - len(prompt)
        ids = [0] * pad + prompt
        depth = 0
        for chain in engine._chain_keys(ids, pad, max(P // engine.bs - 1,
                                                      0)):
            if chain not in engine._prefix_cache:
                break
            depth += 1
        return depth

    def _dispatch_to(self, rep: Replica, req: GatewayRequest, now: float):
        queue_s = now - req.submitted_at
        # one child span per engine attempt (reroute re-dispatches mint a
        # fresh one): the engine binds its rid to this context, so the
        # attempt's whole timeline carries the shared trace_id
        ctx = req.trace.child() if req.trace is not None else None
        try:
            rid = rep.engine.add_request(
                req.prompt, req.max_new_tokens,
                on_token=self._make_on_token(rep, req), trace_ctx=ctx,
                **req.sampling)
        except (ValueError, TypeError, NotImplementedError) as e:
            # a structurally unservable request (prompt over max_len,
            # sampling knobs the engine rejects): terminal "failed", the
            # loop keeps running
            req.error = repr(e)
            self._finalize(req, "failed", now)
            self._emit("failed", gid=req.gid, replica=rep.name,
                       error=repr(e), **self._trace_fields(req))
            return
        req.engine_rid = rid
        req.replica = rep.name
        req.dispatched_at = now
        req.status = "dispatched"
        rep.inflight[rid] = req
        self._stats.add("dispatched")
        self._stats.observe("queue_seconds", queue_s)
        self._emit("dispatch", gid=req.gid, replica=rep.name,
                   queue_s=queue_s, priority=req.priority,
                   **self._trace_fields(req, ctx))

    def _make_on_token(self, rep: Replica, req: GatewayRequest):
        """The engine-facing streaming callback: forwards to the user's
        ``on_token`` under the GATEWAY id, tracks first-token/TTFT, and
        translates the engines' two sentinel signals — replay
        (``None, False``) resets the stream, terminal (``None, True``)
        resolves to expired/cancelled per what triggered the cancel."""
        def cb(_rid, tok, done):
            if tok is None and not done:
                # engine-level preemption replay (paged pool pressure):
                # reset and forward — the rerun re-delivers from token one
                req.tokens = []
                req.first_token_at = None
                req.replays += 1
                if req.on_token is not None:
                    req.on_token(req.gid, None, False)
                return
            if tok is None and done:
                rep.inflight.pop(req.engine_rid, None)
                if req._rerouting:
                    return          # quarantine path signals separately
                now = self._clock()
                if req._pending_expiry is not None:
                    req.error = req._pending_expiry
                    req._pending_expiry = None
                    self._finalize(req, "expired", now)      # forwards the
                else:                                        # terminal sig
                    self._finalize(req, "cancelled", now)
                return
            if req.first_token_at is None:
                # TTFT is observed into the histogram at FINISH, not here:
                # a preemption/reroute would roll this attempt back, and
                # the histogram carries one sample per request — the
                # surviving attempt (the Tracer's documented semantics)
                req.first_token_at = self._clock()
            req.tokens.append(int(tok))
            if req.on_token is not None:
                req.on_token(req.gid, int(tok), done)
        return cb

    def _harvest(self):
        for rep in self._replicas.values():
            self._harvest_replica(rep)

    def _harvest_replica(self, rep: Replica):
        if not hasattr(rep.engine, "pop_finished"):
            return
        for rid, tokens in rep.engine.pop_finished().items():
            req = rep.inflight.pop(rid, None)
            if req is None:
                continue            # not gateway-managed (direct client)
            req.tokens = list(tokens)       # engine list is authoritative
            if req.first_token_at is not None:
                ttft = req.first_token_at - req.submitted_at
                self._stats.observe("ttft_seconds", ttft)
                if self._slo is not None:
                    self._slo.observe("ttft_s", ttft)
            self._finalize(req, "finished", self._clock(), signal=False)
            self._finished[req.gid] = req.tokens

    def _reroute_inflight(self, rep: Replica):
        """Quarantine re-admission: completed work is harvested (never
        replayed), everything else is cancelled on the replica and
        re-queued at the FRONT of its priority queue, oldest first, after
        the documented replay signal."""
        self._harvest_replica(rep)
        moved = sorted(rep.inflight.items(),
                       key=lambda kv: kv[1].submitted_at, reverse=True)
        for rid, req in moved:
            req._rerouting = True
            try:
                rep.engine.cancel(rid)
            except Exception as e:  # noqa: BLE001 — a wedged replica's
                # host state is best-effort; the request reroutes anyway
                self._log.debug("gateway: cancel on quarantined %s "
                                "failed: %r", rep.name, e)
            finally:
                req._rerouting = False
            rep.inflight.pop(rid, None)
            req.engine_rid = None
            req.replica = None
            req.tokens = []
            req.first_token_at = None
            req.replays += 1
            req.status = "queued"
            if req.on_token is not None:
                try:
                    req.on_token(req.gid, None, False)     # replay signal
                except Exception:  # noqa: BLE001 — a raising consumer must
                    # not strand the replica's remaining in-flight requests
                    self._log.exception(
                        "gateway on_token replay signal failed for %d",
                        req.gid)
            self._queues[req.priority].appendleft(req)
            self._queued_tokens[req.priority] += req.est_tokens
            self._stats.add("rerouted")
            self._emit("reroute", gid=req.gid, from_replica=rep.name,
                       **self._trace_fields(req))

    def _unqueue(self, req: GatewayRequest):
        q = self._queues[req.priority]
        try:
            q.remove(req)
        except ValueError:
            return
        self._queued_tokens[req.priority] -= req.est_tokens

    def _finalize(self, req: GatewayRequest, status: str, now: float,
                  signal: bool = True):
        """Terminal transition.  ``signal=True`` delivers the clean
        end-of-stream ``on_token(gid, None, True)`` to the consumer —
        every early termination (shed/expired/cancelled/failed) signals;
        natural completion does not (the engine already delivered the
        last token with ``done=True``)."""
        req.status = status
        req.finished_at = now
        self._stats.add(status)
        if self._slo is not None:
            self._slo.count(status)
        if status == "finished":
            # the trace's explicit terminal marker (shed/expired/cancel/
            # failed already emit their own) — the stitched root span
            # ends here
            self._emit("finish", gid=req.gid, tokens=len(req.tokens),
                       replica=req.replica, replays=req.replays,
                       **self._trace_fields(req))
        self._terminal_order.append(req.gid)
        while len(self._terminal_order) > self.request_history:
            old = self._terminal_order.popleft()
            stale = self._requests.get(old)
            if stale is not None and stale.done:
                del self._requests[old]
        if signal and req.on_token is not None:
            try:
                req.on_token(req.gid, None, True)
            except Exception:  # noqa: BLE001 — consumer bugs must not
                # break the dispatch loop
                self._log.exception(
                    "gateway on_token terminal signal failed for %d",
                    req.gid)

    def _emit(self, what: str, **fields):
        if self.tracer is None:
            return
        self.tracer.emit("gateway", what=what, **fields)

    # --------------------------------------------------------- telemetry --

    def queue_depths(self) -> Dict[int, Dict[str, int]]:
        return {pri: {"depth": len(q),
                      "queued_tokens": self._queued_tokens[pri]}
                for pri, q in enumerate(self._queues)}

    def gateway_snapshot(self) -> Dict[str, Any]:
        """JSON-able live view — what ``ops_server``'s ``/gateway`` route
        serves: replica states, queue depths, counters, latency
        percentiles."""
        h_q = self._stats.histogram("queue_seconds")
        h_t = self._stats.histogram("ttft_seconds")
        counters = {k: v for k, v in self._stats.snapshot().items()}
        return {
            "replicas": [rep.to_dict() for rep in self._replicas.values()],
            "queues": self.queue_depths(),
            "counters": counters,
            # bucket-resolution estimates (utils.stats.Histogram); exact
            # sample percentiles ride the tracer / request handles
            "queue_s": {"p50": h_q.percentile(0.50),
                        "p99": h_q.percentile(0.99)},
            "ttft_s": {"p50": h_t.percentile(0.50),
                       "p99": h_t.percentile(0.99)},
        }

    summary = gateway_snapshot

    def metrics(self) -> Dict[str, float]:
        out = dict(self._stats.snapshot())
        out["queued"] = float(sum(len(q) for q in self._queues))
        out["inflight"] = float(sum(len(rep.inflight)
                                    for rep in self._replicas.values()))
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_gateway") -> str:
        return _prometheus_text(
            self._stats, namespace=namespace,
            extra_gauges={
                "queued": sum(len(q) for q in self._queues),
                "inflight": sum(len(rep.inflight)
                                for rep in self._replicas.values()),
                "replicas_active": sum(
                    1 for rep in self._replicas.values()
                    if rep.state == ACTIVE)})
